"""Streaming JSON reader/writer + declare-fields helper (json.h parity).

The reference ships its own JSON layer (include/dmlc/json.h): a pull
tokenizer (``JSONReader``, json.h:43 — BeginObject/NextObjectItem,
BeginArray/NextArrayItem, ReadString/ReadNumber with line-tracked errors),
a structured writer (``JSONWriter``, json.h:188 — multi-line objects,
inline arrays, WriteObjectKeyValue), and a typed declare-fields helper
(``JSONObjectReadHelper``, json.h:310 — DeclareField/DeclareOptionalField
+ ReadAllFields with unknown-key and missing-required errors).

This is the Python rebuild of that surface: the same pull-parser shape
(no DOM required — values are read as they are pulled, so a huge nested
document streams), plus ``read_value``/``write_value`` conveniences for
plain Python trees. Parameter.save/load rides it (params/parameter.py),
giving the helper its real call site.
"""

from __future__ import annotations

import codecs
import io as _io
import math
from typing import Any, Dict, Optional, Union

from dmlc_tpu.utils.logging import DMLCError

_WS = " \t\r\n"
_ESCAPES = {
    '"': '"', "\\": "\\", "/": "/", "b": "\b", "f": "\f",
    "n": "\n", "r": "\r", "t": "\t",
}
_ESCAPES_OUT = {v: "\\" + k for k, v in _ESCAPES.items() if k != "/"}


class JSONReader:
    """Pull tokenizer over a str, bytes, or readable stream (json.h:43).

    Usage mirrors the reference::

        reader.begin_object()
        while (key := reader.next_object_item()) is not None:
            value = reader.read_value()

        reader.begin_array()
        while reader.next_array_item():
            item = reader.read_number()
    """

    def __init__(self, source: Union[str, bytes, Any]):
        if isinstance(source, bytes):
            source = source.decode("utf-8")
        if isinstance(source, str):
            self._read = _io.StringIO(source).read
        elif hasattr(source, "read"):
            # byte streams decode incrementally: a multi-byte UTF-8
            # character split across read(1) calls must not error
            decoder = codecs.getincrementaldecoder("utf-8")()

            def _read(n: int, _src=source, _dec=decoder) -> str:
                out = ""
                while len(out) < n:
                    chunk = _src.read(1)
                    if not chunk:
                        break
                    if isinstance(chunk, str):
                        out += chunk
                    else:
                        out += _dec.decode(chunk)
                return out

            self._read = _read
        else:
            raise TypeError("JSONReader wants str, bytes or a stream")
        self._peeked: Optional[str] = None
        self.line = 1  # line counter for error messages (json.h:160)
        # scope_counter_ equivalent: items consumed in the current scope
        self._scope_counts: list = []

    # ---- char-level core ----------------------------------------------

    def _next_char(self) -> str:
        if self._peeked is not None:
            c, self._peeked = self._peeked, None
        else:
            c = self._read(1)
        if c == "\n":
            self.line += 1
        return c

    def _peek_char(self) -> str:
        if self._peeked is None:
            self._peeked = self._read(1)
        return self._peeked

    def _next_nonspace(self) -> str:
        while True:
            c = self._next_char()
            if c == "":
                raise self._error("unexpected end of input")
            if c not in _WS:
                return c

    def _peek_nonspace(self) -> str:
        while True:
            c = self._peek_char()
            if c == "":
                raise self._error("unexpected end of input")
            if c not in _WS:
                return c
            self._next_char()

    def _expect(self, want: str) -> None:
        got = self._next_nonspace()
        if got != want:
            raise self._error(f"expected {want!r}, got {got!r}")

    def _error(self, msg: str) -> DMLCError:
        return DMLCError(f"JSON parse error at line {self.line}: {msg}")

    # ---- token surface (json.h:62-111) --------------------------------

    def read_string(self) -> str:
        self._expect('"')
        out = []
        while True:
            c = self._next_char()
            if c == "":
                raise self._error("unterminated string")
            if c == '"':
                return "".join(out)
            if c == "\\":
                esc = self._next_char()
                if esc == "u":
                    out.append(self._read_unicode_escape())
                elif esc in _ESCAPES:
                    out.append(_ESCAPES[esc])
                else:
                    raise self._error(f"bad escape \\{esc}")
            else:
                out.append(c)

    def _read_unicode_escape(self) -> str:
        """\\uXXXX after the backslash-u; combines surrogate pairs (the
        ensure_ascii encoding of non-BMP characters)."""
        code = int("".join(self._next_char() for _ in range(4)), 16)
        if 0xD800 <= code < 0xDC00:
            if self._next_char() == "\\" and self._next_char() == "u":
                low = int("".join(self._next_char() for _ in range(4)), 16)
                if 0xDC00 <= low < 0xE000:
                    return chr(
                        0x10000 + ((code - 0xD800) << 10) + (low - 0xDC00)
                    )
            raise self._error("lone high surrogate in \\u escape")
        return chr(code)

    def read_number(self) -> Union[int, float]:
        buf = [self._next_nonspace()]
        while True:
            c = self._peek_char()
            if c and (c.isdigit() or c in "+-.eE"):
                buf.append(self._next_char())
            else:
                break
        text = "".join(buf)
        try:
            if any(ch in text for ch in ".eE"):
                return float(text)
            return int(text)
        except ValueError as err:
            raise self._error(f"bad number {text!r}") from err

    def read_bool(self) -> bool:
        c = self._peek_nonspace()
        word = "true" if c == "t" else "false"
        for expect in word:
            if self._next_char() != expect:
                raise self._error(f"expected {word!r}")
        return word == "true"

    def read_null(self) -> None:
        for expect in "null":
            got = self._next_nonspace() if expect == "n" else self._next_char()
            if got != expect:
                raise self._error("expected 'null'")
        return None

    def begin_object(self) -> None:
        self._expect("{")
        self._scope_counts.append(0)

    def begin_array(self) -> None:
        self._expect("[")
        self._scope_counts.append(0)

    def next_object_item(self) -> Optional[str]:
        """The key of the next item, or None at object end (json.h:104)."""
        c = self._peek_nonspace()
        if c == "}":
            self._next_char()
            self._scope_counts.pop()
            return None
        if self._scope_counts[-1] > 0:
            self._expect(",")
            if self._peek_nonspace() == "}":  # tolerate trailing close
                self._next_char()
                self._scope_counts.pop()
                return None
        self._scope_counts[-1] += 1
        key = self.read_string()
        self._expect(":")
        return key

    def next_array_item(self) -> bool:
        c = self._peek_nonspace()
        if c == "]":
            self._next_char()
            self._scope_counts.pop()
            return False
        if self._scope_counts[-1] > 0:
            self._expect(",")
            if self._peek_nonspace() == "]":
                self._next_char()
                self._scope_counts.pop()
                return False
        self._scope_counts[-1] += 1
        return True

    # ---- typed read (json.h:119 Read<ValueType>) ----------------------

    def read_value(self) -> Any:
        """Read any JSON value into Python types (dict/list/str/num/...)."""
        c = self._peek_nonspace()
        if c == "{":
            out: Dict[str, Any] = {}
            self.begin_object()
            while (key := self.next_object_item()) is not None:
                out[key] = self.read_value()
            return out
        if c == "[":
            items = []
            self.begin_array()
            while self.next_array_item():
                items.append(self.read_value())
            return items
        if c == '"':
            return self.read_string()
        if c == "t" or c == "f":
            return self.read_bool()
        if c == "n":
            return self.read_null()
        return self.read_number()


class JSONWriter:
    """Structured writer (json.h:188): multi-line objects with indent,
    arrays inline by default, strings escaped."""

    def __init__(self, stream=None, indent: int = 2):
        self._out = stream if stream is not None else _io.StringIO()
        if not hasattr(self._out, "write"):
            raise TypeError(
                f"JSONWriter sink must be writable, got "
                f"{type(self._out).__name__}"
            )
        self._binary: Optional[bool] = None  # detected on first write
        self._indent = indent
        self._scopes: list = []  # [count of items written per open scope]
        self._multi: list = []

    def getvalue(self) -> str:
        if isinstance(self._out, _io.StringIO):
            return self._out.getvalue()
        raise DMLCError("getvalue() only on the default string sink")

    def _w(self, text: str) -> None:
        out = self._out
        if self._binary is None:
            # detect once: the io.Stream surface takes bytes, text files str
            try:
                out.write(text)
                self._binary = False
                return
            except TypeError:
                self._binary = True
        if self._binary:
            out.write(text.encode("utf-8"))
        else:
            out.write(text)

    def _newline_indent(self) -> None:
        self._w("\n" + " " * (self._indent * len(self._scopes)))

    def write_string(self, s: str) -> None:
        out = ['"']
        for ch in s:
            if ch in _ESCAPES_OUT:
                out.append(_ESCAPES_OUT[ch])
            elif ord(ch) < 0x20:
                out.append(f"\\u{ord(ch):04x}")
            else:
                out.append(ch)
        out.append('"')
        self._w("".join(out))

    def write_number(self, v: Union[int, float]) -> None:
        if isinstance(v, bool):  # bool is an int subclass; order matters
            self._w("true" if v else "false")
        elif isinstance(v, float):
            if not math.isfinite(v):
                # repr() would emit bare inf/nan — invalid JSON that no
                # reader accepts; fail at write time, not load time
                raise DMLCError(
                    f"JSON cannot encode non-finite float {v!r}"
                )
            self._w(repr(v))
        else:
            self._w(str(v))

    def begin_object(self, multi_line: bool = True) -> None:
        self._w("{")
        self._scopes.append(0)
        self._multi.append(multi_line)

    def end_object(self) -> None:
        count = self._scopes.pop()
        multi = self._multi.pop()
        if multi and count:
            self._newline_indent()
        self._w("}")

    def write_object_keyvalue(self, key: str, value: Any) -> None:
        if self._scopes[-1] > 0:
            self._w(",")
        if self._multi[-1]:
            self._newline_indent()
        self._scopes[-1] += 1
        self.write_string(key)
        self._w(": ")
        self.write_value(value)

    def begin_array(self, multi_line: bool = False) -> None:
        self._w("[")
        self._scopes.append(0)
        self._multi.append(multi_line)

    def end_array(self) -> None:
        count = self._scopes.pop()
        multi = self._multi.pop()
        if multi and count:
            self._newline_indent()
        self._w("]")

    def write_array_item(self, value: Any) -> None:
        if self._scopes[-1] > 0:
            self._w(",")
            if not self._multi[-1]:
                self._w(" ")
        if self._multi[-1]:
            self._newline_indent()
        self._scopes[-1] += 1
        self.write_value(value)

    def write_value(self, value: Any) -> None:
        """Write any Python tree of dict/list/str/num/bool/None."""
        if value is None:
            self._w("null")
        elif isinstance(value, bool):
            self._w("true" if value else "false")
        elif isinstance(value, (int, float)):
            self.write_number(value)
        elif isinstance(value, str):
            self.write_string(value)
        elif isinstance(value, dict):
            self.begin_object()
            for k, v in value.items():
                self.write_object_keyvalue(str(k), v)
            self.end_object()
        elif isinstance(value, (list, tuple)):
            self.begin_array()
            for item in value:
                self.write_array_item(item)
            self.end_array()
        else:
            raise DMLCError(
                f"JSONWriter cannot encode {type(value).__name__}"
            )


class JSONObjectReadHelper:
    """Declare-fields reader (json.h:310)::

        helper = JSONObjectReadHelper()
        helper.declare_field("name", str)
        helper.declare_optional_field("count", int, default=0)
        values = helper.read_all_fields(reader)

    ``ftype`` may be a type (isinstance-checked after read_value) or a
    callable ``f(reader) -> value`` for custom decoding. Unknown keys and
    missing required fields raise, matching ReadAllFields (json.h:336).
    """

    def __init__(self):
        self._fields: Dict[str, tuple] = {}

    def declare_field(self, key: str, ftype) -> None:
        self._fields[key] = (ftype, False, None)

    def declare_optional_field(self, key: str, ftype, default=None) -> None:
        self._fields[key] = (ftype, True, default)

    def read_all_fields(self, reader: JSONReader) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        reader.begin_object()
        while (key := reader.next_object_item()) is not None:
            spec = self._fields.get(key)
            if spec is None:
                raise DMLCError(
                    f"JSONObjectReadHelper: unknown field {key!r} "
                    f"(declared: {sorted(self._fields)})"
                )
            ftype = spec[0]
            if isinstance(ftype, type):
                value = reader.read_value()
                if ftype in (int, float) and isinstance(value, (int, float)) \
                        and not isinstance(value, bool):
                    value = ftype(value)
                elif not isinstance(value, ftype) or (
                    ftype is not bool and isinstance(value, bool)
                ):
                    raise DMLCError(
                        f"field {key!r}: expected {ftype.__name__}, got "
                        f"{type(value).__name__}"
                    )
            else:
                value = ftype(reader)
            out[key] = value
        for key, (_t, optional, default) in self._fields.items():
            if key not in out:
                if not optional:
                    raise DMLCError(
                        f"JSONObjectReadHelper: required field {key!r} "
                        f"missing"
                    )
                out[key] = default
        return out


# ---- module-level conveniences (the dmlc::JSON loads/dumps shape) ---------


def loads(text: Union[str, bytes]) -> Any:
    return JSONReader(text).read_value()


def dumps(value: Any, indent: int = 2) -> str:
    writer = JSONWriter(indent=indent)
    writer.write_value(value)
    return writer.getvalue()


def load(stream) -> Any:
    return JSONReader(stream).read_value()


def dump(value: Any, stream, indent: int = 2) -> None:
    JSONWriter(stream, indent=indent).write_value(value)
