"""I/O layer: streams, filesystems, URI dispatch, serialization.

Reference capabilities mirrored: include/dmlc/io.h (Stream/SeekStream/
Serializable + factory), src/io/filesys.h (FileSystem plugin interface),
src/io.cc (protocol dispatch), include/dmlc/memory_io.h (in-memory streams),
include/dmlc/serializer.h (typed binary serialization), src/io/uri_spec.h.
RecordIO and InputSplit live in sibling modules of this package.
"""

from dmlc_tpu.io.stream import (
    Stream,
    SeekStream,
    MemoryStream,
    FixedMemoryStream,
    Serializable,
)
from dmlc_tpu.io.serializer import save_obj, load_obj
from dmlc_tpu.io.filesystem import (
    URI,
    FileInfo,
    FileSystem,
    LocalFileSystem,
    MemoryFileSystem,
    register_filesystem,
    get_filesystem,
    create_stream,
    create_stream_for_read,
    expand_uri_patterns,
    list_split_files,
)
from dmlc_tpu.io.uri_spec import URISpec
from dmlc_tpu.io.recordio import (
    RECORDIO_MAGIC,
    RecordIOWriter,
    RecordIOReader,
    RecordIOChunkReader,
    build_index,
)
from dmlc_tpu.io.input_split import InputSplit, create_input_split

__all__ = [
    "Stream",
    "SeekStream",
    "MemoryStream",
    "FixedMemoryStream",
    "Serializable",
    "save_obj",
    "load_obj",
    "URI",
    "FileInfo",
    "FileSystem",
    "LocalFileSystem",
    "MemoryFileSystem",
    "register_filesystem",
    "get_filesystem",
    "create_stream",
    "create_stream_for_read",
    "expand_uri_patterns",
    "list_split_files",
    "URISpec",
    "RECORDIO_MAGIC",
    "build_index",
    "RecordIOWriter",
    "RecordIOReader",
    "RecordIOChunkReader",
    "InputSplit",
    "create_input_split",
]
