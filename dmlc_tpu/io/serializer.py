"""Typed binary serialization of Python values over a Stream.

Capability parity with include/dmlc/serializer.h: the reference dispatches at
compile time over PODs, strings, and nested STL containers
(serializer.h:69-120+); unsupported types are a compile error
(UndefinedSerializerFor:96-98). Here the dispatch is over runtime tags with a
deterministic little-endian wire format (NOT pickle: no code execution on
load, stable across processes — suitable for checkpoint/cache files).

Supported: None, bool, int (signed 64-bit), float (f64), bytes, str, list,
tuple, dict, set, and numpy ndarrays (dtype + shape + raw buffer).
"""

from __future__ import annotations

import struct
from typing import Any

import numpy as np

from dmlc_tpu.io.stream import Stream

_T_NONE = 0
_T_BOOL = 1
_T_INT = 2
_T_FLOAT = 3
_T_BYTES = 4
_T_STR = 5
_T_LIST = 6
_T_TUPLE = 7
_T_DICT = 8
_T_SET = 9
_T_NDARRAY = 10
_T_BIGINT = 11  # ints outside int64 range, as length-prefixed big-endian


class SerializationError(TypeError):
    """Unsupported type (the runtime analog of UndefinedSerializerFor)."""


def save_obj(stream: Stream, obj: Any) -> None:
    _save(stream, obj)


def load_obj(stream: Stream) -> Any:
    return _load(stream)


def _tag(stream: Stream, t: int) -> None:
    stream.write(struct.pack("<B", t))


def _save(s: Stream, obj: Any) -> None:
    if obj is None:
        _tag(s, _T_NONE)
    elif isinstance(obj, bool):
        _tag(s, _T_BOOL)
        s.write_fmt("B", 1 if obj else 0)
    elif isinstance(obj, int):
        if -(2**63) <= obj < 2**63:
            _tag(s, _T_INT)
            s.write_fmt("q", obj)
        else:
            _tag(s, _T_BIGINT)
            nbytes = (obj.bit_length() + 8) // 8  # room for sign
            s.write_uint64(nbytes)
            s.write(obj.to_bytes(nbytes, "little", signed=True))
    elif isinstance(obj, float):
        _tag(s, _T_FLOAT)
        s.write_fmt("d", obj)
    elif isinstance(obj, bytes):
        _tag(s, _T_BYTES)
        s.write_bytes_prefixed(obj)
    elif isinstance(obj, str):
        _tag(s, _T_STR)
        s.write_bytes_prefixed(obj.encode("utf-8"))
    elif isinstance(obj, list):
        _tag(s, _T_LIST)
        s.write_uint64(len(obj))
        for item in obj:
            _save(s, item)
    elif isinstance(obj, tuple):
        _tag(s, _T_TUPLE)
        s.write_uint64(len(obj))
        for item in obj:
            _save(s, item)
    elif isinstance(obj, dict):
        _tag(s, _T_DICT)
        s.write_uint64(len(obj))
        for key, val in obj.items():
            _save(s, key)
            _save(s, val)
    elif isinstance(obj, (set, frozenset)):
        _tag(s, _T_SET)
        s.write_uint64(len(obj))
        # Deterministic order for reproducible bytes.
        for item in sorted(obj, key=repr):
            _save(s, item)
    elif isinstance(obj, np.ndarray):
        _tag(s, _T_NDARRAY)
        # record the ORIGINAL shape: ascontiguousarray promotes 0-d
        # arrays to (1,), which would silently rewrite scalar params
        # (e.g. a bias of shape ()) across a save/load round trip
        arr = np.ascontiguousarray(obj)
        s.write_bytes_prefixed(str(arr.dtype).encode("ascii"))
        s.write_uint64(obj.ndim)
        for dim in obj.shape:
            s.write_uint64(dim)
        s.write(arr.tobytes())
    elif isinstance(obj, (np.integer,)):
        _save(s, int(obj))
    elif isinstance(obj, (np.floating,)):
        _save(s, float(obj))
    else:
        raise SerializationError(
            f"No serializer defined for type {type(obj).__name__}"
        )


def _load(s: Stream) -> Any:
    tag = struct.unpack("<B", s.read_exact(1))[0]
    if tag == _T_NONE:
        return None
    if tag == _T_BOOL:
        return bool(s.read_fmt("B"))
    if tag == _T_INT:
        return s.read_fmt("q")
    if tag == _T_BIGINT:
        nbytes = s.read_uint64()
        return int.from_bytes(s.read_exact(nbytes), "little", signed=True)
    if tag == _T_FLOAT:
        return s.read_fmt("d")
    if tag == _T_BYTES:
        return s.read_bytes_prefixed()
    if tag == _T_STR:
        return s.read_bytes_prefixed().decode("utf-8")
    if tag == _T_LIST:
        return [_load(s) for _ in range(s.read_uint64())]
    if tag == _T_TUPLE:
        return tuple(_load(s) for _ in range(s.read_uint64()))
    if tag == _T_DICT:
        n = s.read_uint64()
        out = {}
        for _ in range(n):
            key = _load(s)
            out[key] = _load(s)
        return out
    if tag == _T_SET:
        return {_load(s) for _ in range(s.read_uint64())}
    if tag == _T_NDARRAY:
        dtype = np.dtype(s.read_bytes_prefixed().decode("ascii"))
        ndim = s.read_uint64()
        shape = tuple(s.read_uint64() for _ in range(ndim))
        count = int(np.prod(shape)) if shape else 1
        data = s.read_exact(count * dtype.itemsize)
        return np.frombuffer(data, dtype=dtype).reshape(shape).copy()
    raise SerializationError(f"Corrupt stream: unknown tag {tag}")
