"""Object-store filesystems: GCS (``gs://``) and S3 (``s3://``).

GCS plays the role the reference's hand-rolled S3 client plays
(src/io/s3_filesys.{h,cc}) — SURVEY §2.3 "TPU note" — and both backends
reproduce that client's behavior shape:

- lazy-seek range-GET read streams that reconnect and continue on short
  reads/dropped connections, ≤50 retries with 100 ms backoff
  (CURLReadStreamBase, s3_filesys.cc:219-445, retry loop :319-342)
- buffered multi-part upload writers: S3 multipart (Init ?uploads /
  Upload part+ETag / CompleteMultipartUpload, s3_filesys.cc:760-806) and
  the GCS equivalent, resumable upload sessions; per-REST-call retry ≤3
  (s3_filesys.cc:577,712-751); write buffer size via
  ``DMLC_S3_WRITE_BUFFER_MB`` / ``DMLC_GCS_WRITE_BUFFER_MB`` (default 64,
  s3_filesys.cc:569-576)
- ListObjects with prefix+delimiter mapped to list_directory
  (s3_filesys.cc:814-906)
- credentials from env: ``S3_ACCESS_KEY``/``S3_SECRET_KEY`` or
  ``AWS_ACCESS_KEY_ID``/``AWS_SECRET_ACCESS_KEY`` (+ session token,
  region, endpoint — s3_filesys.cc:909-962); GCS bearer token from
  ``GCS_OAUTH_TOKEN``. Anonymous (unsigned) access when unset, so public
  buckets and test fakes work without credentials.

Request signing is AWS Signature V4 (the modern replacement for the
reference's V2 HMAC-SHA1 signing, s3_filesys.cc:90-122). Endpoints are
overridable (``S3_ENDPOINT``/``AWS_ENDPOINT_URL``, ``GCS_ENDPOINT_URL``)
so the suite tests against an in-process fake server — the hermetic
coverage the reference lacked (SURVEY §4: live-service-only testing).
"""

from __future__ import annotations

import datetime as _dt
import hashlib
import hmac
import io
import json
import os
import threading
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Tuple

from dmlc_tpu.io.filesystem import (
    FILE_TYPE_DIR,
    FILE_TYPE_FILE,
    FileInfo,
    FileSystem,
    RangedReadStream,
    URI,
    read_range_with_retry,
    register_filesystem,
)
from dmlc_tpu.io.stream import SeekStream, Stream
from dmlc_tpu.utils.logging import DMLCError, check, log_info

READ_MAX_RETRY = 50          # s3_filesys.cc:319-342
READ_RETRY_SLEEP_S = 0.1
WRITE_MAX_RETRY = 3          # s3_filesys.cc:577
DEFAULT_WRITE_BUFFER_MB = 64  # s3_filesys.cc:573-575


def _http(req: urllib.request.Request, timeout: float = 60,
          verify_ssl: bool = True):
    if not verify_ssl:
        import ssl

        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        return urllib.request.urlopen(req, timeout=timeout, context=ctx)
    return urllib.request.urlopen(req, timeout=timeout)


def _keepalive_get(url: str, headers: Dict[str, str], timeout: float = 60,
                   verify_ssl: bool = True, max_redirects: int = 5):
    """Bounded ranged GET over a per-thread persistent connection.

    urllib opens (and the server tears down) a fresh TCP connection per
    request; at one bounded range-GET every 8 MiB that is a connect
    handshake, a server accept-thread spawn and a slow-start restart per
    range. Connections are keyed per (scheme, netloc) in thread-local
    storage — the readahead pool's fetch threads each keep their own. A
    stale kept-alive connection (server closed between ranges) retries
    once on a fresh one; 3xx follows Location like urlopen's redirect
    handler; HTTP >= 400 raises urllib's HTTPError so the shared retry
    loop's status handling applies unchanged.

    Only for bounded ranges (the body is always drained): open-ended
    stream responses must NOT share these connections — an undrained body
    would poison the next request on the same thread. When an egress
    proxy applies to the URL's scheme, falls back to urlopen (which
    routes through ProxyHandler).
    """
    import http.client
    import ssl

    if urllib.request.getproxies().get(
        urllib.parse.urlsplit(url).scheme
    ):
        req = urllib.request.Request(url, headers=headers)
        return _http(req, timeout=timeout, verify_ssl=verify_ssl)

    conns = getattr(_keepalive_local, "conns", None)
    if conns is None:
        conns = _keepalive_local.conns = {}
    last_err = None
    for _hop in range(max_redirects):
        parsed = urllib.parse.urlsplit(url)
        key = (parsed.scheme, parsed.netloc)
        path = parsed.path + (f"?{parsed.query}" if parsed.query else "")
        resp = None
        for _attempt in range(2):
            conn = conns.get(key)
            if conn is None:
                if parsed.scheme == "https":
                    ctx = ssl.create_default_context()
                    if not verify_ssl:
                        ctx.check_hostname = False
                        ctx.verify_mode = ssl.CERT_NONE
                    conn = http.client.HTTPSConnection(
                        parsed.netloc, timeout=timeout, context=ctx
                    )
                else:
                    conn = http.client.HTTPConnection(
                        parsed.netloc, timeout=timeout
                    )
                conns[key] = conn
            try:
                conn.request("GET", path, headers=headers)
                resp = conn.getresponse()
                break
            except (OSError, http.client.HTTPException) as err:
                # stale keep-alive: drop, retry once on a fresh connection
                conn.close()
                conns.pop(key, None)
                last_err = err
        if resp is None:
            raise last_err
        if 300 <= resp.status < 400:
            location = resp.headers.get("Location")
            resp.read()
            resp.close()
            if not location:
                raise urllib.error.HTTPError(
                    url, resp.status, resp.reason, resp.headers, None
                )
            url = urllib.parse.urljoin(url, location)
            continue
        if resp.status >= 400:
            body = resp.read()
            resp.close()
            raise urllib.error.HTTPError(
                url, resp.status, resp.reason, resp.headers,
                io.BytesIO(body),
            )
        return resp
    raise DMLCError(f"too many redirects fetching {url}")


_keepalive_local = threading.local()


# ---------------------------------------------------------------------------
# AWS Signature V4
# ---------------------------------------------------------------------------


def _sigv4_headers(
    method: str,
    url: str,
    region: str,
    access_key: str,
    secret_key: str,
    payload: bytes = b"",
    session_token: Optional[str] = None,
    now: Optional[_dt.datetime] = None,
) -> Dict[str, str]:
    """AWS SigV4 signing headers for one S3 request (public spec; replaces
    the reference's V2 `Sign`, s3_filesys.cc:90-122)."""
    parsed = urllib.parse.urlsplit(url)
    host = parsed.netloc
    now = now or _dt.datetime.now(_dt.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(payload).hexdigest()

    headers = {
        "host": host,
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
    }
    if session_token:
        headers["x-amz-security-token"] = session_token
    signed_names = ";".join(sorted(headers))
    canonical_headers = "".join(
        f"{k}:{headers[k].strip()}\n" for k in sorted(headers)
    )
    # canonical query: sorted by key, values URL-encoded
    query_pairs = urllib.parse.parse_qsl(
        parsed.query, keep_blank_values=True
    )
    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(query_pairs)
    )
    # parsed.path is already percent-encoded as sent on the wire (the
    # builders quote keys before signing); re-quoting would double-encode
    # and break the signature for keys with special characters
    canonical_path = parsed.path or "/"
    canonical_request = "\n".join([
        method, canonical_path, canonical_query, canonical_headers,
        signed_names, payload_hash,
    ])
    scope = f"{datestamp}/{region}/s3/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest(),
    ])

    def _hmac(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k_date = _hmac(b"AWS4" + secret_key.encode(), datestamp)
    k_region = _hmac(k_date, region)
    k_service = _hmac(k_region, "s3")
    k_signing = _hmac(k_service, "aws4_request")
    signature = hmac.new(
        k_signing, string_to_sign.encode(), hashlib.sha256
    ).hexdigest()
    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_names}, Signature={signature}"
    )
    del headers["host"]  # urllib sets it
    return headers


# ---------------------------------------------------------------------------
# Shared read stream: lazy seek + reconnecting range-GET
# ---------------------------------------------------------------------------


class ObjectWriteStream(Stream):
    """Buffered part-upload writer (WriteStream, s3_filesys.cc:557-812):
    buffer until the part size, upload parts as they fill, finalize on
    close. Subclasses implement the three REST steps."""

    def __init__(self, part_bytes: int):
        self._buf = bytearray()
        self._part_bytes = part_bytes
        self._closed = False

    def read(self, nbytes: int) -> bytes:
        raise IOError("write-only stream")

    def write(self, data: bytes) -> None:
        check(not self._closed, "stream closed")
        self._buf.extend(data)
        while len(self._buf) >= self._part_bytes:
            part = bytes(self._buf[: self._part_bytes])
            del self._buf[: self._part_bytes]
            self._upload_part(part, last=False)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._upload_part(bytes(self._buf), last=True)
        self._buf.clear()
        self._finalize()

    def __del__(self):  # reference WriteStream uploads on destruction
        try:
            self.close()
        except Exception as err:  # pragma: no cover - GC-time path
            # an exception can't propagate from __del__, but a failed
            # finalize means the object was never created — say so loudly
            log_info("ERROR: object upload lost in destructor: %s", err)

    def _upload_part(self, data: bytes, last: bool) -> None:
        raise NotImplementedError

    def _finalize(self) -> None:
        raise NotImplementedError


def _write_call(fn, site: str, what: str):
    """One mutating REST call (s3_filesys.cc:712-751 shape) under the
    shared retry policy, with an ``io.write`` faultpoint inside the
    retried region so injected write faults exercise the same recovery
    path real ones do.

    This replaces the old ``_retry_call`` helper, which slept a full
    backoff *after* the final failed attempt and treated throttling
    (429/408) as fatal because ``code < 500`` — both fixed by
    :class:`dmlc_tpu.resilience.RetryPolicy`'s loop and classifier.
    """
    from dmlc_tpu.resilience import RetryPolicy, faultpoint

    def attempt():
        faultpoint("io.write")
        return fn()

    return RetryPolicy(
        max_attempts=WRITE_MAX_RETRY, base_s=READ_RETRY_SLEEP_S
    ).call(attempt, site, display=what)


# ---------------------------------------------------------------------------
# Base class: bucket/key plumbing shared by GCS and S3
# ---------------------------------------------------------------------------


class _ObjectStoreBase(FileSystem):
    def _bucket_key(self, path: URI) -> Tuple[str, str]:
        return path.host, path.name.lstrip("/")

    def _display(self, path: URI) -> str:
        return path.str_full()

    def _open_ranged(self, path: URI, start: int, end: Optional[int] = None):
        """GET from ``start``; bounded ``[start, end)`` when end given."""
        raise NotImplementedError

    @staticmethod
    def _range_header(start: int, end: Optional[int]) -> str:
        return f"bytes={start}-" if end is None else f"bytes={start}-{end - 1}"

    def read_range(
        self, path: URI, offset: int, length: int, cancelled=None, into=None
    ):
        """One bounded range GET per call — the parallel-readahead
        primitive, with per-range retry (s3_filesys.cc:319-342 shape).
        With ``into`` (writable memoryview) the body lands in caller
        memory and the byte count is returned."""
        return read_range_with_retry(
            lambda start, end: self._open_ranged(path, start, end),
            offset, length, self._display(path),
            max_retry=READ_MAX_RETRY, retry_sleep_s=READ_RETRY_SLEEP_S,
            cancelled=cancelled, into=into,
        )

    def _stat_object(self, path: URI) -> Optional[int]:
        """size, or None when no such object."""
        raise NotImplementedError

    def _list(self, bucket: str, prefix: str, delimiter: str):
        """→ (files: [(key, size)], prefixes: [str])."""
        raise NotImplementedError

    # ---- FileSystem interface ----------------------------------------

    def get_path_info(self, path: URI) -> FileInfo:
        size = self._stat_object(path)
        if size is not None:
            return FileInfo(path=path, size=size, type=FILE_TYPE_FILE)
        # directory probe: any key under the prefix? (TryGetPathInfo,
        # s3_filesys.cc:970-989 lists with the path as prefix)
        bucket, key = self._bucket_key(path)
        prefix = key.rstrip("/") + "/" if key else ""
        files, prefixes = self._list(bucket, prefix, "/")
        if files or prefixes:
            return FileInfo(path=path, size=0, type=FILE_TYPE_DIR)
        raise FileNotFoundError(self._display(path))

    def list_directory(self, path: URI) -> List[FileInfo]:
        bucket, key = self._bucket_key(path)
        prefix = key.rstrip("/") + "/" if key else ""
        files, prefixes = self._list(bucket, prefix, "/")
        out: List[FileInfo] = []
        for sub_key, size in files:
            if sub_key == prefix:  # the directory marker object itself
                continue
            sub = URI(path.protocol, path.host, "/" + sub_key)
            out.append(FileInfo(path=sub, size=size, type=FILE_TYPE_FILE))
        for p in prefixes:
            sub = URI(path.protocol, path.host, "/" + p.rstrip("/"))
            out.append(FileInfo(path=sub, size=0, type=FILE_TYPE_DIR))
        out.sort(key=lambda fi: fi.path.name)
        return out

    def open_for_read(self, path: URI, allow_null: bool = False) -> Optional[SeekStream]:
        size = self._stat_object(path)
        if size is None:
            if allow_null:
                return None
            raise FileNotFoundError(self._display(path))
        return RangedReadStream(
            lambda start: self._open_ranged(path, start), size,
            self._display(path),
            max_retry=READ_MAX_RETRY, retry_sleep_s=READ_RETRY_SLEEP_S,
        )

    def open(self, path: URI, flag: str) -> Stream:
        check(flag in ("r", "w"), "object stores support flags r/w, not %s", flag)
        if flag == "r":
            stream = self.open_for_read(path)
            assert stream is not None
            return stream
        return self._open_write(path)

    def _open_write(self, path: URI) -> Stream:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# S3
# ---------------------------------------------------------------------------


class S3FileSystem(_ObjectStoreBase):
    """``s3://bucket/key`` via path-style REST + SigV4."""

    def __init__(self):
        env = os.environ
        # credential env precedence mirrors s3_filesys.cc:909-962
        self.access_key = env.get("S3_ACCESS_KEY") or env.get("AWS_ACCESS_KEY_ID")
        self.secret_key = env.get("S3_SECRET_KEY") or env.get(
            "AWS_SECRET_ACCESS_KEY"
        )
        self.session_token = env.get("S3_SESSION_TOKEN") or env.get(
            "AWS_SESSION_TOKEN"
        )
        self.region = env.get("S3_REGION") or env.get("AWS_REGION", "us-east-1")
        endpoint = env.get("S3_ENDPOINT") or env.get("AWS_ENDPOINT_URL")
        self.endpoint = (endpoint or f"https://s3.{self.region}.amazonaws.com").rstrip("/")
        self.verify_ssl = env.get("S3_VERIFY_SSL", "1") != "0"
        self.part_bytes = (
            int(env.get("DMLC_S3_WRITE_BUFFER_MB", DEFAULT_WRITE_BUFFER_MB))
            << 20
        )

    def _url(self, bucket: str, key: str, query: str = "") -> str:
        path = f"/{bucket}/{urllib.parse.quote(key)}"
        return self.endpoint + path + (f"?{query}" if query else "")

    def _request(
        self, method: str, url: str, payload: bytes = b"",
        headers: Optional[Dict[str, str]] = None, timeout: float = 60,
    ):
        hdrs = dict(headers or {})
        if self.access_key and self.secret_key:
            hdrs.update(_sigv4_headers(
                method, url, self.region, self.access_key, self.secret_key,
                payload, self.session_token,
            ))
        req = urllib.request.Request(
            url, data=payload if payload else None, headers=hdrs, method=method
        )
        return _http(req, timeout=timeout, verify_ssl=self.verify_ssl)

    # ---- reads -------------------------------------------------------

    def _open_ranged(self, path: URI, start: int, end: Optional[int] = None):
        bucket, key = self._bucket_key(path)
        url = self._url(bucket, key)
        hdrs = {"Range": self._range_header(start, end)}
        if self.access_key and self.secret_key:
            hdrs.update(_sigv4_headers(
                "GET", url, self.region, self.access_key, self.secret_key,
                b"", self.session_token,
            ))
        if end is not None:  # bounded: body fully drained, safe to reuse
            return _keepalive_get(url, hdrs, verify_ssl=self.verify_ssl)
        req = urllib.request.Request(url, headers=hdrs)
        return _http(req, verify_ssl=self.verify_ssl)

    def _stat_object(self, path: URI) -> Optional[int]:
        bucket, key = self._bucket_key(path)
        if not key:
            return None
        try:
            with self._request("HEAD", self._url(bucket, key)) as resp:
                return int(resp.headers.get("Content-Length", 0))
        except urllib.error.HTTPError as err:
            if err.code in (404, 403):
                return None
            raise

    def _list(self, bucket: str, prefix: str, delimiter: str):
        files: List[Tuple[str, int]] = []
        prefixes: List[str] = []
        token = None
        while True:
            q = [
                ("list-type", "2"),
                ("prefix", prefix),
                ("delimiter", delimiter),
            ]
            if token:
                q.append(("continuation-token", token))
            query = urllib.parse.urlencode(q)
            url = f"{self.endpoint}/{bucket}?{query}"
            with self._request("GET", url) as resp:
                tree = ET.fromstring(resp.read())
            ns = ""
            if tree.tag.startswith("{"):
                ns = tree.tag[: tree.tag.index("}") + 1]
            for item in tree.findall(f"{ns}Contents"):
                files.append((
                    item.findtext(f"{ns}Key"),
                    int(item.findtext(f"{ns}Size", "0")),
                ))
            for item in tree.findall(f"{ns}CommonPrefixes"):
                prefixes.append(item.findtext(f"{ns}Prefix"))
            token = tree.findtext(f"{ns}NextContinuationToken")
            if not token:
                break
        return files, prefixes

    # ---- writes: multipart upload (s3_filesys.cc:760-806) ------------

    class _S3WriteStream(ObjectWriteStream):
        def __init__(self, fs: "S3FileSystem", path: URI):
            super().__init__(fs.part_bytes)
            self._fs = fs
            self._path = path
            self._upload_id: Optional[str] = None
            self._etags: List[str] = []
            self._part_no = 0

        def _init_upload(self) -> None:
            fs, (bucket, key) = self._fs, self._fs._bucket_key(self._path)
            url = fs._url(bucket, key, "uploads")

            def call():
                with fs._request("POST", url) as resp:
                    tree = ET.fromstring(resp.read())
                ns = tree.tag[: tree.tag.index("}") + 1] if tree.tag.startswith("{") else ""
                return tree.findtext(f"{ns}UploadId")

            self._upload_id = _write_call(call, "io.s3.write", "InitiateMultipartUpload")
            check(self._upload_id, "no UploadId in InitiateMultipartUpload reply")

        def _upload_part(self, data: bytes, last: bool) -> None:
            fs, (bucket, key) = self._fs, self._fs._bucket_key(self._path)
            if self._upload_id is None and last and self._part_no == 0:
                # whole object fits one buffer: plain PUT
                url = fs._url(bucket, key)

                def put():
                    with fs._request("PUT", url, payload=data):
                        pass

                _write_call(put, "io.s3.write", "PutObject")
                self._part_no = -1  # mark single-shot done
                return
            if self._upload_id is None:
                self._init_upload()
            self._part_no += 1
            n = self._part_no
            url = fs._url(
                bucket, key, f"partNumber={n}&uploadId={self._upload_id}"
            )

            def call():
                with fs._request("PUT", url, payload=data) as resp:
                    return resp.headers.get("ETag", "")

            self._etags.append(_write_call(call, "io.s3.write", f"UploadPart {n}"))

        def _finalize(self) -> None:
            if self._part_no <= 0:  # single-shot PUT already complete
                return
            fs, (bucket, key) = self._fs, self._fs._bucket_key(self._path)
            url = fs._url(bucket, key, f"uploadId={self._upload_id}")
            parts = "".join(
                f"<Part><PartNumber>{i + 1}</PartNumber><ETag>{etag}</ETag></Part>"
                for i, etag in enumerate(self._etags)
            )
            body = (
                f"<CompleteMultipartUpload>{parts}</CompleteMultipartUpload>"
            ).encode()

            def call():
                with fs._request("POST", url, payload=body):
                    pass

            _write_call(call, "io.s3.write", "CompleteMultipartUpload")

    def _open_write(self, path: URI) -> Stream:
        return self._S3WriteStream(self, path)

    def delete(self, path: URI) -> None:
        bucket, key = self._bucket_key(path)

        def call():
            with self._request("DELETE", self._url(bucket, key)):
                pass

        _write_call(call, "io.s3.delete", "DeleteObject")


# ---------------------------------------------------------------------------
# GCS
# ---------------------------------------------------------------------------


class GCSFileSystem(_ObjectStoreBase):
    """``gs://bucket/object`` via the XML API for data + JSON API for
    listing, resumable uploads for writes."""

    def __init__(self):
        env = os.environ
        self.endpoint = env.get(
            "GCS_ENDPOINT_URL", "https://storage.googleapis.com"
        ).rstrip("/")
        self.token = env.get("GCS_OAUTH_TOKEN")
        self.part_bytes = (
            int(env.get("DMLC_GCS_WRITE_BUFFER_MB", DEFAULT_WRITE_BUFFER_MB))
            << 20
        )
        # resumable chunks must be 256 KiB aligned (and nonzero)
        self.part_bytes = max(256 << 10,
                              self.part_bytes - self.part_bytes % (256 << 10))

    def _headers(self, extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        hdrs = dict(extra or {})
        if self.token:
            hdrs["Authorization"] = f"Bearer {self.token}"
        return hdrs

    def _media_url(self, bucket: str, key: str) -> str:
        return f"{self.endpoint}/{bucket}/{urllib.parse.quote(key)}"

    def _open_ranged(self, path: URI, start: int, end: Optional[int] = None):
        bucket, key = self._bucket_key(path)
        url = self._media_url(bucket, key)
        hdrs = self._headers({"Range": self._range_header(start, end)})
        if end is not None:  # bounded: body fully drained, safe to reuse
            return _keepalive_get(url, hdrs)
        return _http(urllib.request.Request(url, headers=hdrs))

    def _stat_object(self, path: URI) -> Optional[int]:
        bucket, key = self._bucket_key(path)
        if not key:
            return None
        req = urllib.request.Request(
            self._media_url(bucket, key), headers=self._headers(),
            method="HEAD",
        )
        try:
            with _http(req) as resp:
                return int(resp.headers.get("Content-Length", 0))
        except urllib.error.HTTPError as err:
            if err.code in (404, 403):
                return None
            raise

    def delete(self, path: URI) -> None:
        bucket, key = self._bucket_key(path)

        def call():
            req = urllib.request.Request(
                self._media_url(bucket, key),
                headers=self._headers(),
                method="DELETE",
            )
            with _http(req):
                pass

        _write_call(call, "io.gcs.delete", "gcs DeleteObject")

    def _list(self, bucket: str, prefix: str, delimiter: str):
        files: List[Tuple[str, int]] = []
        prefixes: List[str] = []
        page_token = None
        while True:
            q = [("prefix", prefix), ("delimiter", delimiter)]
            if page_token:
                q.append(("pageToken", page_token))
            url = (
                f"{self.endpoint}/storage/v1/b/{bucket}/o?"
                + urllib.parse.urlencode(q)
            )
            req = urllib.request.Request(url, headers=self._headers())
            with _http(req) as resp:
                doc = json.loads(resp.read())
            for item in doc.get("items", []):
                files.append((item["name"], int(item.get("size", 0))))
            prefixes.extend(doc.get("prefixes", []))
            page_token = doc.get("nextPageToken")
            if not page_token:
                break
        return files, prefixes

    # ---- writes: resumable upload session ----------------------------

    class _GCSWriteStream(ObjectWriteStream):
        def __init__(self, fs: "GCSFileSystem", path: URI):
            super().__init__(fs.part_bytes)
            self._fs = fs
            self._path = path
            self._session: Optional[str] = None
            self._offset = 0

        def _start_session(self) -> None:
            fs, (bucket, key) = self._fs, self._fs._bucket_key(self._path)
            url = (
                f"{fs.endpoint}/upload/storage/v1/b/{bucket}/o?"
                + urllib.parse.urlencode(
                    [("uploadType", "resumable"), ("name", key)]
                )
            )

            def call():
                req = urllib.request.Request(
                    url, data=b"", headers=fs._headers(), method="POST"
                )
                with _http(req) as resp:
                    return resp.headers.get("Location") or resp.headers.get(
                        "X-GUploader-UploadID"
                    )

            self._session = _write_call(call, "io.gcs.write", "start resumable upload")
            check(self._session, "no session URI from resumable upload start")

        def _upload_part(self, data: bytes, last: bool) -> None:
            if self._session is None:
                self._start_session()
            start = self._offset
            end = start + len(data) - 1
            total = str(start + len(data)) if last else "*"
            if data:
                content_range = f"bytes {start}-{end}/{total}"
            else:
                content_range = f"bytes */{total}"
            fs = self._fs

            def call():
                req = urllib.request.Request(
                    self._session, data=data,
                    headers=fs._headers({"Content-Range": content_range}),
                    method="PUT",
                )
                try:
                    with _http(req):
                        pass
                except urllib.error.HTTPError as err:
                    if err.code != 308:  # 308 = resume incomplete (expected)
                        raise
            _write_call(call, "io.gcs.write", "resumable upload chunk")
            self._offset += len(data)

        def _finalize(self) -> None:
            pass  # the final chunk (total != "*") completes the session

    def _open_write(self, path: URI) -> Stream:
        return self._GCSWriteStream(self, path)


register_filesystem("s3://", lambda uri: S3FileSystem())
register_filesystem("gs://", lambda uri: GCSFileSystem())
register_filesystem("gcs://", lambda uri: GCSFileSystem())
