"""InputSplit: the data-parallel sharding primitive.

Capability parity with the reference's InputSplit machinery (io.h:135-282,
src/io/input_split_base.{h,cc}, line_split, recordio_split,
indexed_recordio_split, threaded_input_split.h, cached_input_split.h,
include/dmlc/input_split_shuffle.h):

- part k of n over a multi-file byte-ranged dataset with **exactly-once**
  record coverage: partition boundaries are aligned byte offsets, then moved
  forward to the next record boundary (ResetPartition,
  input_split_base.cc:30-64) so every record belongs to exactly one part
- chunked reading that never yields partial records: a tail ``overflow``
  buffer holds bytes after the last record head, and the chunk buffer doubles
  until it holds at least one whole record (ReadChunk/Chunk::Load,
  input_split_base.cc:211-279)
- record types: "text" (newline records), "recordio" (magic-framed binary),
  "indexed_recordio" (record-count-equal parts via an index file, optional
  per-epoch shuffle with a seeded RNG — indexed_recordio_split.cc)
- decorators: background-thread chunk prefetch (threaded_input_split.h,
  capacity 2, applied by default), first-epoch disk cache
  (cached_input_split.h, selected by ``#cachefile``), and "global" shuffle by
  visiting ``num_shuffle_parts`` sub-splits in seeded random order per epoch
  (input_split_shuffle.h)

TPU framing: one part per TPU host feeds that host's chips; parts are the
per-process shards a jax.sharding mesh consumes (see dmlc_tpu.device).
"""

from __future__ import annotations

import struct
import sys
from typing import Iterator, List, Optional, Tuple

import numpy as np

from dmlc_tpu import obs
from dmlc_tpu.io import recordio as _rio
from dmlc_tpu.io.filesystem import (
    FileInfo,
    create_stream,
    get_filesystem,
    list_split_files,
)
from dmlc_tpu.io.stream import SeekStream, Stream
from dmlc_tpu.io.uri_spec import URISpec
from dmlc_tpu.utils.logging import DMLCError, check, check_eq
from dmlc_tpu.utils.threaded_iter import ThreadedIter

# 8 MiB chunk buffer, matching kBufferSize = 2UL<<20 uint32 words x 4 bytes
# (src/io/input_split_base.h:39-40).
DEFAULT_CHUNK_BYTES = (2 << 20) * 4

# process-wide ingest byte counter (docs/observability.md); splits of every
# flavor funnel raw reads through it
_M_READ = obs.registry().counter(
    "dmlc_io_read_bytes_total", "payload bytes ingested by source",
    source="input_split")


class InputSplit:
    """Abstract record/chunk pull API (io.h:135-282)."""

    def next_record(self) -> Optional[bytes]:
        """Next single record, or None at end of this part's data."""
        raise NotImplementedError

    def next_chunk(self) -> Optional[bytes]:
        """Next multi-record chunk (for multithreaded parsing), or None."""
        raise NotImplementedError

    def next_batch(self, n_records: int) -> Optional[bytes]:
        """Chunk of ~n_records records where supported (io.h:210)."""
        return self.next_chunk()

    def before_first(self) -> None:
        raise NotImplementedError

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        raise NotImplementedError

    def hint_chunk_size(self, chunk_size: int) -> None:
        pass

    def get_total_size(self) -> int:
        raise NotImplementedError

    def records(self) -> Iterator[bytes]:
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec

    def chunks(self) -> Iterator[bytes]:
        while True:
            chunk = self.next_chunk()
            if chunk is None:
                return
            yield chunk

    def close(self) -> None:
        pass


class InputSplitBase(InputSplit):
    """Multi-file byte-range splitting core (src/io/input_split_base.*)."""

    def __init__(
        self,
        uri: str,
        align_bytes: int,
        recurse_directories: bool = False,
    ):
        self._files: List[FileInfo] = list_split_files(uri, recurse_directories)
        self._file_offset = [0]
        for info in self._files:
            check(
                info.size % align_bytes == 0,
                "file %s does not align by %d bytes",
                info.path.str_full(),
                align_bytes,
            )
            self._file_offset.append(self._file_offset[-1] + info.size)
        self._align = align_bytes
        self._chunk_bytes = DEFAULT_CHUNK_BYTES
        self._fs_stream: Optional[SeekStream] = None
        self._file_ptr = 0
        self._offset_begin = 0
        self._offset_end = 0
        self._offset_curr = 0
        self._overflow = b""
        self._pending_records: List[bytes] = []
        self._pending_idx = 0

    # ---- subclass hooks -----------------------------------------------
    def seek_record_begin(self, stream: Stream) -> int:
        """Read forward to the next record start; return bytes skipped."""
        raise NotImplementedError

    def find_last_record_begin(self, buf: bytes) -> int:
        """Offset of the last record head in buf (0 when none found)."""
        raise NotImplementedError

    def extract_records(self, chunk: bytes) -> List[bytes]:
        """Split a whole-records chunk into individual records."""
        raise NotImplementedError

    # ---- partitioning (input_split_base.cc:30-64) ----------------------
    def reset_partition(self, part_index: int, num_parts: int) -> None:
        ntotal = self._file_offset[-1]
        nstep = (ntotal + num_parts - 1) // num_parts
        align = self._align
        nstep = ((nstep + align - 1) // align) * align
        begin = min(nstep * part_index, ntotal)
        end = min(nstep * (part_index + 1), ntotal)
        self._offset_begin = begin
        self._offset_end = end
        self._offset_curr = begin
        if begin == end:
            self._close_stream()
            self.before_first()
            return
        # Find the exact end: seek to the raw boundary in the file containing
        # it and extend to the next record begin.
        file_end = self._file_index_for(end)
        if end != self._file_offset[file_end]:
            check(end > self._file_offset[file_end], "bad end offset")
            check(file_end < len(self._files), "bad end offset")
            fs = self._open(file_end)
            fs.seek(end - self._file_offset[file_end])
            self._offset_end = end + self.seek_record_begin(fs)
            fs.close()
        # Find the exact begin likewise.
        self._file_ptr = self._file_index_for(begin)
        fs = self._open(self._file_ptr)
        if begin != self._file_offset[self._file_ptr]:
            fs.seek(begin - self._file_offset[self._file_ptr])
            self._offset_begin = begin + self.seek_record_begin(fs)
        fs.close()
        self.before_first()

    def _file_index_for(self, offset: int) -> int:
        # index i with file_offset[i] <= offset < file_offset[i+1]
        import bisect

        return bisect.bisect_right(self._file_offset, offset) - 1

    def _open(self, file_index: int) -> SeekStream:
        path = self._files[file_index].path
        stream = get_filesystem(path).open_for_read(path)
        assert stream is not None
        return stream

    def _close_stream(self) -> None:
        if self._fs_stream is not None:
            self._fs_stream.close()
            self._fs_stream = None

    def before_first(self) -> None:
        self._pending_records = []
        self._pending_idx = 0
        self._overflow = b""
        if self._offset_begin >= self._offset_end:
            return
        self._close_stream()
        self._file_ptr = self._file_index_for(self._offset_begin)
        self._fs_stream = self._open(self._file_ptr)
        self._fs_stream.seek(self._offset_begin - self._file_offset[self._file_ptr])
        self._offset_curr = self._offset_begin

    # ---- raw reading across file boundaries (input_split_base.cc:177-209)
    def _read_range(self, size: int) -> bytes:
        if self._offset_begin >= self._offset_end or self._fs_stream is None:
            return b""
        size = min(size, self._offset_end - self._offset_curr)
        if size <= 0:
            return b""
        parts: List[bytes] = []
        nleft = size
        while nleft > 0:
            data = self._fs_stream.read(nleft)
            if data:
                parts.append(data)
                nleft -= len(data)
                self._offset_curr += len(data)
                continue
            # End of current file: verify bookkeeping, move to the next file.
            check_eq(
                self._offset_curr,
                self._file_offset[self._file_ptr + 1],
                "file offset not calculated correctly",
            )
            if self._file_ptr + 1 >= len(self._files):
                break
            self._file_ptr += 1
            self._close_stream()
            self._fs_stream = self._open(self._file_ptr)
        _M_READ.inc(size - nleft)
        if len(parts) == 1:
            return parts[0]
        return b"".join(parts)

    # ---- chunk loading (ReadChunk + Chunk::Load semantics) -------------
    def _load_chunk(self) -> Optional[bytes]:
        """Next chunk containing only whole records, or None at end."""
        target = self._chunk_bytes
        overflow = self._overflow
        self._overflow = b""
        data = self._read_range(target - len(overflow))
        # fast path: no pending overflow join needed
        buf = (overflow + data) if overflow else data
        if not buf:
            return None
        while True:
            if len(buf) < target:
                # End of the partition range: remainder is the final chunk
                # (its end was extended to a record boundary).
                return bytes(buf)
            pos = self.find_last_record_begin(buf)
            if pos != 0:
                self._overflow = bytes(buf[pos:])
                return bytes(memoryview(buf)[:pos])
            # No record boundary inside: grow and read more
            # (Chunk::Load doubling, input_split_base.cc:241-258).
            target *= 2
            buf = buf + self._read_range(target - len(buf))

    # ---- public API ----------------------------------------------------
    def next_chunk(self) -> Optional[bytes]:
        return self._load_chunk()

    def next_record(self) -> Optional[bytes]:
        while self._pending_idx >= len(self._pending_records):
            chunk = self._load_chunk()
            if chunk is None:
                return None
            self._pending_records = self.extract_records(chunk)
            self._pending_idx = 0
        rec = self._pending_records[self._pending_idx]
        self._pending_idx += 1
        return rec

    def hint_chunk_size(self, chunk_size: int) -> None:
        self._chunk_bytes = max(chunk_size, self._align)

    def get_total_size(self) -> int:
        return self._file_offset[-1]

    def close(self) -> None:
        self._close_stream()


class LineSplitter(InputSplitBase):
    """Text records, one per line (src/io/line_split.{h,cc}).

    Runs of ``\\n``/``\\r`` collapse: empty lines do not produce records,
    matching the reference's ExtractNextRecord scan (line_split.cc:36-55).
    """

    def __init__(self, uri: str, recurse_directories: bool = False):
        super().__init__(uri, align_bytes=1, recurse_directories=recurse_directories)

    def seek_record_begin(self, stream: Stream) -> int:
        nstep = 0
        # scan to the first end-of-line (line_split.cc:9-26)
        while True:
            c = stream.read(1)
            if not c:
                return nstep
            nstep += 1
            if c in (b"\n", b"\r"):
                break
        # consume the rest of the newline run (not counted toward the skip
        # except for the newline bytes themselves)
        while True:
            c = stream.read(1)
            if not c:
                return nstep
            if c not in (b"\n", b"\r"):
                break
            nstep += 1
        return nstep

    def find_last_record_begin(self, buf: bytes) -> int:
        pos_n = buf.rfind(b"\n", 1)
        pos_r = buf.rfind(b"\r", 1)
        pos = max(pos_n, pos_r)
        return pos + 1 if pos >= 0 else 0

    def extract_records(self, chunk: bytes) -> List[bytes]:
        return [line for line in chunk.splitlines() if line]


class RecordIOSplitter(InputSplitBase):
    """Magic-framed binary records (src/io/recordio_split.{h,cc})."""

    def __init__(self, uri: str, recurse_directories: bool = False):
        super().__init__(uri, align_bytes=4, recurse_directories=recurse_directories)

    def seek_record_begin(self, stream: Stream) -> int:
        # Scan forward one u32 at a time for a record head: magic followed by
        # an lrecord with cflag 0 or 1 (recordio_split.cc:9-24).
        nstep = 0
        while True:
            word = stream.read(4)
            if not word:
                return nstep
            nstep += 4
            if struct.unpack("<I", word)[0] == _rio.RECORDIO_MAGIC:
                lrec_b = stream.read(4)
                check(len(lrec_b) == 4, "invalid recordio format")
                nstep += 4
                cflag = _rio.decode_flag(struct.unpack("<I", lrec_b)[0])
                if cflag in (0, 1):
                    return nstep - 8

    def find_last_record_begin(self, buf: bytes) -> int:
        check_eq(len(buf) % 4, 0, "recordio chunk must stay 4B-aligned")
        words = np.frombuffer(buf, dtype="<u4")
        hits = np.nonzero(words[:-1] == _rio.RECORDIO_MAGIC)[0]
        if hits.size:
            flags = (words[hits + 1] >> 29) & 7
            good = hits[(flags == 0) | (flags == 1)]
            if good.size:
                pos = int(good[-1]) << 2
                if pos != 0:
                    return pos
        return 0

    def extract_records(self, chunk: bytes) -> List[bytes]:
        return list(_rio.RecordIOChunkReader(chunk))


class IndexedRecordIOSplitter(InputSplitBase):
    """Record-count-equal partitioning of RecordIO via an index file
    (src/io/indexed_recordio_split.{h,cc}).

    The index file holds whitespace-separated ``index offset`` pairs; offsets
    are sorted and turned into (offset, size) spans (ReadIndexFile,
    indexed_recordio_split.cc:43-61). Partitioning assigns equal **record
    counts** per part; ``shuffle=True`` visits the part's records in a fresh
    seeded permutation each epoch (BeforeFirst, indexed_recordio_split.cc,
    seed mixed with kRandMagic=111).
    """

    K_RAND_MAGIC = 111

    def __init__(
        self,
        uri: str,
        index_uri: str,
        batch_size: int = 256,
        shuffle: bool = False,
        seed: int = 0,
        recurse_directories: bool = False,
    ):
        super().__init__(uri, align_bytes=4, recurse_directories=recurse_directories)
        self._index: List[Tuple[int, int]] = []  # (offset, size)
        self._read_index_file(index_uri)
        self.batch_size = batch_size
        self._shuffle = shuffle
        # One persistent engine seeded once, reshuffled every epoch — like the
        # reference's member mt19937 (indexed_recordio_split.h:55-57).
        self._rng = np.random.Generator(np.random.MT19937(self.K_RAND_MAGIC + seed))
        self._index_begin = 0
        self._index_end = 0
        self._current = 0
        self._n_overflow = 0
        self._permutation: List[int] = []

    def _read_index_file(self, index_uri: str) -> None:
        stream = create_stream(index_uri, "r")
        assert stream is not None
        text_parts = []
        while True:
            data = stream.read(1 << 20)
            if not data:
                break
            text_parts.append(data)
        stream.close()
        tokens = b"".join(text_parts).split()
        check(len(tokens) % 2 == 0, "invalid index file: odd token count")
        offsets = sorted(int(tokens[i + 1]) for i in range(0, len(tokens), 2))
        check(len(offsets) > 0, "empty index file")
        total = self._file_offset[-1]
        for i, off in enumerate(offsets):
            nxt = offsets[i + 1] if i + 1 < len(offsets) else total
            self._index.append((off, nxt - off))

    # Record-count partitioning (indexed_recordio_split.cc:12-41).
    def reset_partition(self, part_index: int, num_parts: int) -> None:
        ntotal = len(self._index)
        nstep = (ntotal + num_parts - 1) // num_parts
        if part_index * nstep >= ntotal:
            self._index_begin = self._index_end = 0
            self._offset_begin = self._offset_end = 0
            self.before_first()
            return
        self._index_begin = part_index * nstep
        self._index_end = min((part_index + 1) * nstep, ntotal)
        self._offset_begin = self._index[self._index_begin][0]
        last_off, last_size = self._index[self._index_end - 1]
        self._offset_end = last_off + last_size
        self.before_first()

    def before_first(self) -> None:
        self._pending_records = []
        self._pending_idx = 0
        self._overflow = b""
        self._n_overflow = 0
        if self._shuffle:
            perm = np.arange(self._index_begin, self._index_end)
            self._rng.shuffle(perm)
            self._permutation = [int(i) for i in perm]
            self._current = 0
        else:
            self._current = self._index_begin
        self._offset_curr = self._offset_begin
        self._close_stream()
        if self._offset_begin < self._offset_end:
            self._file_ptr = self._file_index_for(self._offset_begin)
            self._fs_stream = self._open(self._file_ptr)
            self._fs_stream.seek(
                self._offset_begin - self._file_offset[self._file_ptr]
            )

    def _read_span(self, offset: int, size: int) -> bytes:
        """Read an absolute [offset, offset+size) span across files."""
        file_idx = self._file_index_for(offset)
        if self._fs_stream is None or file_idx != self._file_ptr:
            self._close_stream()
            self._file_ptr = file_idx
            self._fs_stream = self._open(file_idx)
        self._fs_stream.seek(offset - self._file_offset[file_idx])
        self._offset_curr = offset
        parts: List[bytes] = []
        nleft = size
        while nleft > 0:
            data = self._fs_stream.read(nleft)
            if not data:
                check(
                    self._file_ptr + 1 < len(self._files),
                    "index points past end of data",
                )
                self._file_ptr += 1
                self._close_stream()
                self._fs_stream = self._open(self._file_ptr)
                continue
            parts.append(data)
            nleft -= len(data)
            self._offset_curr += len(data)
        _M_READ.inc(size)
        return b"".join(parts)

    def next_batch(self, n_records: int) -> Optional[bytes]:
        """A chunk holding the next ~n_records records (honors the reference's
        n_overflow carry: a short batch is completed before a new one starts,
        NextBatchEx indexed_recordio_split.cc:158-211)."""
        n = self._n_overflow if self._n_overflow else n_records
        if self._shuffle:
            out: List[bytes] = []
            n_read = 0
            while n_read < n and self._current < len(self._permutation):
                off, size = self._index[self._permutation[self._current]]
                out.append(self._read_span(off, size))
                self._current += 1
                n_read += 1
            if n_read == 0:
                return None
            self._n_overflow = n - n_read
            return b"".join(out)
        if self._current >= self._index_end:
            return None
        last = min(self._current + n, self._index_end)
        self._n_overflow = self._current + n - last
        begin_off = self._index[self._current][0]
        end_off, end_size = self._index[last - 1]
        span = self._read_span(begin_off, end_off + end_size - begin_off)
        self._current = last
        return span

    def next_chunk(self) -> Optional[bytes]:
        return self.next_batch(self.batch_size)

    def next_record(self) -> Optional[bytes]:
        while self._pending_idx >= len(self._pending_records):
            chunk = self.next_chunk()
            if chunk is None:
                return None
            self._pending_records = list(_rio.RecordIOChunkReader(chunk))
            self._pending_idx = 0
        rec = self._pending_records[self._pending_idx]
        self._pending_idx += 1
        return rec

    def seek_record_begin(self, stream: Stream) -> int:  # pragma: no cover
        raise DMLCError("indexed recordio does not seek by scanning")

    def find_last_record_begin(self, buf: bytes) -> int:  # pragma: no cover
        raise DMLCError("indexed recordio does not split chunks by scanning")

    def extract_records(self, chunk: bytes) -> List[bytes]:
        return list(_rio.RecordIOChunkReader(chunk))


class SingleFileSplit(InputSplit):
    """stdin / single-file fallback without partitioning
    (src/io/single_file_split.h; selected for uri == "stdin",
    src/io.cc:95-97). Text records only."""

    def __init__(self, path: str):
        self._path = path
        self._pending: List[bytes] = []
        self._idx = 0
        self._chunk_bytes = DEFAULT_CHUNK_BYTES
        self._tail = b""
        self._eof = False
        self._stream = None
        self.before_first()

    def _open(self):
        if self._path == "stdin":
            return sys.stdin.buffer
        return open(self._path, "rb")

    def before_first(self) -> None:
        if self._stream is not None and self._path != "stdin":
            self._stream.close()
            self._stream = None
        self._stream = self._open()
        self._pending = []
        self._idx = 0
        self._tail = b""
        self._eof = False

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        check_eq(num_parts, 1, "SingleFileSplit does not support partitioning")
        self.before_first()

    def next_chunk(self) -> Optional[bytes]:
        if self._eof and not self._tail:
            return None
        data = self._stream.read(self._chunk_bytes)
        if not data:
            self._eof = True
            out, self._tail = self._tail, b""
            return out or None
        _M_READ.inc(len(data))
        buf = self._tail + data
        pos = max(buf.rfind(b"\n"), buf.rfind(b"\r")) + 1
        if pos == 0:
            out, self._tail = b"", buf
            # keep reading until we find a boundary or EOF
            nxt = self.next_chunk()
            return nxt
        self._tail = buf[pos:]
        return buf[:pos]

    def next_record(self) -> Optional[bytes]:
        while self._idx >= len(self._pending):
            chunk = self.next_chunk()
            if chunk is None:
                return None
            self._pending = [ln for ln in chunk.splitlines() if ln]
            self._idx = 0
        rec = self._pending[self._idx]
        self._idx += 1
        return rec

    def hint_chunk_size(self, chunk_size: int) -> None:
        self._chunk_bytes = chunk_size

    def get_total_size(self) -> int:
        import os

        if self._path == "stdin":
            return 0
        return os.path.getsize(self._path)


# ---------------------------------------------------------------------------
# Decorators
# ---------------------------------------------------------------------------


class ThreadedInputSplit(InputSplit):
    """Background-thread chunk prefetch, queue capacity 2
    (src/io/threaded_input_split.h:33). Applied by default by the factory."""

    def __init__(self, base: InputSplitBase, capacity: int = 2):
        self._base = base
        self._iter = ThreadedIter(
            self._chunk_source, max_capacity=capacity, name="input-split-prefetch"
        )
        self._pending: List[bytes] = []
        self._idx = 0

    def _chunk_source(self) -> Iterator[bytes]:
        while True:
            chunk = self._base.next_chunk()
            if chunk is None:
                return
            yield chunk

    def next_chunk(self) -> Optional[bytes]:
        return self._iter.next()

    def next_record(self) -> Optional[bytes]:
        while self._idx >= len(self._pending):
            chunk = self.next_chunk()
            if chunk is None:
                return None
            self._pending = self._base.extract_records(chunk)
            self._idx = 0
        rec = self._pending[self._idx]
        self._idx += 1
        return rec

    def before_first(self) -> None:
        self._iter.close()
        self._base.before_first()
        self._iter.before_first()
        self._pending = []
        self._idx = 0

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        self._iter.close()
        self._base.reset_partition(part_index, num_parts)
        self._iter.before_first()
        self._pending = []
        self._idx = 0

    def hint_chunk_size(self, chunk_size: int) -> None:
        self._base.hint_chunk_size(chunk_size)

    def get_total_size(self) -> int:
        return self._base.get_total_size()

    def close(self) -> None:
        self._iter.close()
        self._base.close()


class CachedInputSplit(InputSplit):
    """First epoch streams chunks AND writes ``[u64 size][bytes]`` frames to a
    local cache file; later epochs replay the cache instead of the (possibly
    remote) source (src/io/cached_input_split.h:148-189)."""

    PREFETCH = 16  # cached_input_split.h:151

    def __init__(self, base: InputSplitBase, cache_file: str):
        import os

        self._base = base
        self._cache_file = cache_file
        self._cache_ready = os.path.exists(cache_file)
        self._tmp_file = cache_file + ".tmp"
        self._iter = ThreadedIter(
            self._chunk_source, max_capacity=self.PREFETCH, name="cached-split"
        )

    def _chunk_source(self) -> Iterator[bytes]:
        import os

        if self._cache_ready:
            with open(self._cache_file, "rb") as fp:
                while True:
                    head = fp.read(8)
                    if len(head) < 8:
                        return
                    (size,) = struct.unpack("<Q", head)
                    yield fp.read(size)
        else:
            with open(self._tmp_file, "wb") as out:
                while True:
                    chunk = self._base.next_chunk()
                    if chunk is None:
                        break
                    out.write(struct.pack("<Q", len(chunk)))
                    out.write(chunk)
                    yield chunk
            os.replace(self._tmp_file, self._cache_file)
            self._cache_ready = True

    def next_chunk(self) -> Optional[bytes]:
        return self._iter.next()

    def next_record(self) -> Optional[bytes]:
        raise DMLCError(
            "CachedInputSplit is chunk-only (cached_input_split.h:57-60)"
        )

    def before_first(self) -> None:
        self._iter.close()
        if not self._cache_ready:
            self._base.before_first()
        self._iter.before_first()

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        raise DMLCError("CachedInputSplit cannot repartition after caching")

    def hint_chunk_size(self, chunk_size: int) -> None:
        self._base.hint_chunk_size(chunk_size)

    def get_total_size(self) -> int:
        return self._base.get_total_size()

    def close(self) -> None:
        self._iter.close()
        self._base.close()


class InputSplitShuffle(InputSplit):
    """"Global" shuffle: split this part into ``num_shuffle_parts`` sub-splits
    and visit them in a fresh seeded random order each epoch
    (include/dmlc/input_split_shuffle.h:24-33,138-147)."""

    def __init__(
        self,
        make_split,  # Callable[[int, int], InputSplit] for (sub_part, total)
        part_index: int,
        num_parts: int,
        num_shuffle_parts: int,
        seed: int = 0,
    ):
        self._make_split = make_split
        self._part_index = part_index
        self._num_parts = num_parts
        self._num_shuffle = num_shuffle_parts
        self._rng = np.random.Generator(np.random.MT19937(seed))
        self._split: Optional[InputSplit] = None
        self._order: List[int] = []
        self._pos = 0
        self.before_first()

    def before_first(self) -> None:
        self._order = [
            self._part_index * self._num_shuffle + i for i in range(self._num_shuffle)
        ]
        self._rng.shuffle(self._order)
        self._pos = 0
        self._advance()

    def _advance(self) -> None:
        if self._split is not None:
            self._split.close()
            self._split = None
        if self._pos < len(self._order):
            self._split = self._make_split(
                self._order[self._pos], self._num_parts * self._num_shuffle
            )
            self._pos += 1

    def next_record(self) -> Optional[bytes]:
        while self._split is not None:
            rec = self._split.next_record()
            if rec is not None:
                return rec
            self._advance()
        return None

    def next_chunk(self) -> Optional[bytes]:
        while self._split is not None:
            chunk = self._split.next_chunk()
            if chunk is not None:
                return chunk
            self._advance()
        return None

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        self._part_index = part_index
        self._num_parts = num_parts
        self.before_first()

    def get_total_size(self) -> int:
        return self._split.get_total_size() if self._split else 0

    def close(self) -> None:
        if self._split is not None:
            self._split.close()


# ---------------------------------------------------------------------------
# Factory (io.h:241-281 + src/io.cc:82-131)
# ---------------------------------------------------------------------------


def create_input_split(
    uri: str,
    part_index: int,
    num_parts: int,
    split_type: str = "text",
    *,
    index_uri: str = "",
    shuffle: bool = False,
    seed: int = 0,
    batch_size: int = 256,
    recurse_directories: bool = False,
    num_shuffle_parts: int = 0,
    threaded: bool = True,
) -> InputSplit:
    """InputSplit::Create.

    ``split_type`` ∈ {"text", "recordio", "indexed_recordio"}; a
    ``#cachefile`` suffix on the uri selects the disk-cache decorator
    (src/io.cc:120-125); ``uri == "stdin"`` selects SingleFileSplit
    (src/io.cc:95-97); prefetch is applied by default like the reference.
    ``num_shuffle_parts > 0`` wraps in InputSplitShuffle.
    """
    if uri == "stdin":
        return SingleFileSplit(uri)
    spec = URISpec(uri, part_index, num_parts)
    if num_shuffle_parts > 0:
        check(not spec.cache_file, "shuffle splits do not combine with cache files")

        def make_sub(sub_part: int, total: int) -> InputSplit:
            return create_input_split(
                spec.uri,
                sub_part,
                total,
                split_type,
                index_uri=index_uri,
                batch_size=batch_size,
                recurse_directories=recurse_directories,
                threaded=threaded,
            )

        return InputSplitShuffle(
            make_sub, part_index, num_parts, num_shuffle_parts, seed=seed
        )

    base: InputSplitBase
    if split_type == "text":
        base = LineSplitter(spec.uri, recurse_directories)
    elif split_type == "recordio":
        base = RecordIOSplitter(spec.uri, recurse_directories)
    elif split_type == "indexed_recordio":
        check(bool(index_uri), "indexed_recordio requires index_uri")
        base = IndexedRecordIOSplitter(
            spec.uri,
            index_uri,
            batch_size=batch_size,
            shuffle=shuffle,
            seed=seed,
            recurse_directories=recurse_directories,
        )
    else:
        raise DMLCError(f"unknown input split type {split_type!r}")
    base.reset_partition(part_index, num_parts)
    if spec.cache_file:
        return CachedInputSplit(base, spec.cache_file)
    if threaded:
        return ThreadedInputSplit(base)
    return base
