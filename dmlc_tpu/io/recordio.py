"""RecordIO: the splittable binary record format, bit-compatible on disk.

Capability parity with include/dmlc/recordio.h + src/recordio.cc — files
written here are readable by the reference and vice versa:

- frame: ``[kMagic=0xced7230a (u32 LE)][lrecord (u32 LE)][data][pad to 4B]``
  where ``lrecord = (cflag << 29) | length`` (recordio.h:45-70)
- cflag 0 = whole record; 1/2/3 = start/middle/end parts, produced when the
  payload itself contains the magic word at a 4-byte-aligned offset: the
  writer splits there and drops the embedded magic (WriteRecord,
  recordio.cc:11-51); the reader reassembles re-inserting the magic
  (NextRecord, recordio.cc:53-82)
- records are < 2^29 bytes (recordio.cc:12)
- ``RecordIOChunkReader`` parses records out of an in-memory chunk and can
  subdivide the chunk into ``num_parts`` aligned segments for multi-threaded
  parsing (recordio.cc:101-156)

Implementation is numpy-vectorized (aligned u32 scan) rather than a byte loop.
"""

from __future__ import annotations

import struct
from typing import Iterator, List, Optional, Tuple

import numpy as np

from dmlc_tpu import obs
from dmlc_tpu.io.stream import Stream
from dmlc_tpu.utils.logging import check

RECORDIO_MAGIC = 0xCED7230A
_MAGIC_BYTES = struct.pack("<I", RECORDIO_MAGIC)
_MAX_RECORD = 1 << 29


def encode_lrec(cflag: int, length: int) -> int:
    """(cflag << 29) | length (recordio.h:53-56)."""
    return (cflag << 29) | length


def decode_flag(rec: int) -> int:
    return (rec >> 29) & 7


def decode_length(rec: int) -> int:
    return rec & ((1 << 29) - 1)


def _aligned_magic_positions(data: bytes) -> np.ndarray:
    """4-byte-aligned offsets where the magic word occurs in ``data``
    (vectorized equivalent of the writer's scan loop, recordio.cc:22-27)."""
    lower = (len(data) >> 2) << 2
    if lower == 0:
        return np.empty(0, dtype=np.int64)
    words = np.frombuffer(data, dtype="<u4", count=lower >> 2)
    return (np.nonzero(words == RECORDIO_MAGIC)[0] << 2).astype(np.int64)


class RecordIOWriter:
    """Writes records; splits payloads at embedded magics (recordio.cc:11-51)."""

    def __init__(self, stream: Stream):
        self._stream = stream
        self.except_counter = 0  # number of embedded magics encountered

    def write_record(self, data: bytes) -> None:
        check(len(data) < _MAX_RECORD, "RecordIO only accepts records < 2^29 bytes")
        out: List[bytes] = []
        dptr = 0
        for pos in _aligned_magic_positions(data):
            pos = int(pos)
            lrec = encode_lrec(1 if dptr == 0 else 2, pos - dptr)
            out.append(_MAGIC_BYTES)
            out.append(struct.pack("<I", lrec))
            if pos != dptr:
                out.append(data[dptr:pos])
            dptr = pos + 4
            self.except_counter += 1
        lrec = encode_lrec(3 if dptr != 0 else 0, len(data) - dptr)
        out.append(_MAGIC_BYTES)
        out.append(struct.pack("<I", lrec))
        if len(data) != dptr:
            out.append(data[dptr:])
        pad = (-(len(data) - dptr)) % 4
        if pad:
            out.append(b"\x00" * pad)
        self._stream.write(b"".join(out))

    def write_records(self, records) -> None:
        """Batch write: one native frame pass + one stream write when the
        native core is loaded (cpp/recordio.cc recordio_pack_batch), else a
        loop over write_record."""
        from dmlc_tpu import native

        records = list(records)  # may be a generator; we iterate twice
        packed = native.recordio_pack_records(records)
        if packed is None:
            for rec in records:
                self.write_record(rec)
            return
        lens = np.fromiter((len(r) for r in records), dtype=np.int64,
                           count=len(records))
        check(bool((lens < _MAX_RECORD).all()),
              "RecordIO only accepts records < 2^29 bytes")
        # each embedded magic costs exactly one extra 8-byte header and
        # removes its own 4 bytes from padded payload space; recover the
        # count from the size delta instead of rescanning every record
        plain = 8 * len(records) + int(((lens + 3) & ~3).sum())
        self.except_counter += (len(packed) - plain) // 4
        self._stream.write(packed)


class RecordIOReader:
    """Sequentially reads and reassembles records (recordio.cc:53-82)."""

    def __init__(self, stream: Stream):
        self._stream = stream
        self._eos = False
        self._m_read = obs.registry().counter(
            "dmlc_io_read_bytes_total", "payload bytes ingested by source",
            source="recordio")

    def next_record(self) -> Optional[bytes]:
        if self._eos:
            return None
        parts: List[bytes] = []
        nread = 0
        while True:
            header = self._stream.read(8)
            if len(header) == 0 and not parts:
                self._eos = True
                return None
            check(len(header) == 8, "Invalid RecordIO file: truncated header")
            nread += 8
            magic, lrec = struct.unpack("<II", header)
            check(magic == RECORDIO_MAGIC, "Invalid RecordIO file: bad magic")
            cflag = decode_flag(lrec)
            length = decode_length(lrec)
            upper = (length + 3) & ~3
            if upper:
                payload = self._stream.read_exact(upper)
                parts.append(payload[:length])
                nread += upper
            if cflag in (0, 3):
                break
            parts.append(_MAGIC_BYTES)
        self._m_read.inc(nread)
        return b"".join(parts)

    def __iter__(self) -> Iterator[bytes]:
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec


def _find_next_record_head(data: bytes, begin: int, end: int) -> int:
    """First aligned offset in [begin,end) holding a record head: magic with
    cflag 0 or 1 (FindNextRecordIOHead, recordio.cc:85-99). The scan requires
    a following lrecord word, so it stops 8 bytes before ``end``."""
    check((begin & 3) == 0 and (end & 3) == 0, "chunk bounds must be 4B-aligned")
    if end - begin < 8:
        return end
    words = np.frombuffer(data, dtype="<u4", offset=begin, count=(end - begin) >> 2)
    # candidate positions: words[i] == magic and i+1 < len (p + 1 < pend)
    hits = np.nonzero(words[:-1] == RECORDIO_MAGIC)[0]
    if hits.size:
        flags = (words[hits + 1] >> 29) & 7
        good = hits[(flags == 0) | (flags == 1)]
        if good.size:
            return begin + (int(good[0]) << 2)
    return end


class RecordIOChunkReader:
    """Parse records out of a chunk; optional subdivision into aligned
    part ranges for multithreaded parsing (recordio.cc:101-156)."""

    def __init__(self, chunk: bytes, part_index: int = 0, num_parts: int = 1):
        size = len(chunk)
        nstep = (size + num_parts - 1) // num_parts
        nstep = (nstep + 3) & ~3
        begin = min(size, nstep * part_index)
        end = min(size, nstep * (part_index + 1))
        self._data = chunk
        self._pbegin = _find_next_record_head(chunk, begin, size)
        self._pend = _find_next_record_head(chunk, end, size)
        # native fast path: decode the whole part range in one C pass
        self._decoded: Optional[Tuple[bytes, np.ndarray]] = None
        self._decoded_idx = 0
        if self._pbegin < self._pend:
            from dmlc_tpu import native

            res = native.recordio_unpack_chunk(
                chunk[self._pbegin : self._pend]
            )
            if res is not None:
                data, offsets, consumed = res
                check(consumed == self._pend - self._pbegin,
                      "Invalid RecordIO format (partial frame inside part)")
                self._decoded = (data, offsets)

    def next_record(self) -> Optional[bytes]:
        if self._decoded is not None:
            data, offsets = self._decoded
            i = self._decoded_idx
            if i >= len(offsets) - 1:
                return None
            self._decoded_idx = i + 1
            return data[offsets[i] : offsets[i + 1]]
        if self._pbegin >= self._pend:
            return None
        data = self._data
        magic, lrec = struct.unpack_from("<II", data, self._pbegin)
        check(magic == RECORDIO_MAGIC, "Invalid RecordIO format")
        cflag = decode_flag(lrec)
        length = decode_length(lrec)
        if cflag == 0:
            start = self._pbegin + 8
            self._pbegin = start + ((length + 3) & ~3)
            check(self._pbegin <= self._pend, "Invalid RecordIO format")
            return data[start : start + length]
        check(cflag == 1, "Invalid RecordIO format")
        parts: List[bytes] = []
        while True:
            check(self._pbegin + 8 <= self._pend, "Invalid RecordIO format")
            magic, lrec = struct.unpack_from("<II", data, self._pbegin)
            check(magic == RECORDIO_MAGIC, "Invalid RecordIO format")
            cflag = decode_flag(lrec)
            length = decode_length(lrec)
            start = self._pbegin + 8
            parts.append(data[start : start + length])
            self._pbegin = start + ((length + 3) & ~3)
            if cflag == 3:
                break
            parts.append(_MAGIC_BYTES)
        return b"".join(parts)

    def __iter__(self) -> Iterator[bytes]:
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec


def build_index(uri: str, index_uri: str) -> int:
    """Write an IndexedRecordIO index file for an existing RecordIO file.

    The reference consumes index files but ships no builder (they come from
    downstream tooling like mxnet's im2rec); this walks the framing and
    emits the ``key<TAB>offset`` text format ReadIndexFile expects
    (indexed_recordio_split.cc:43-61), one line per record (multi-part
    records index their first frame). Returns the record count.
    """
    from dmlc_tpu.io.filesystem import create_stream, create_stream_for_read

    entries: List[Tuple[int, int]] = []
    pos = 0
    # bytearray: extend/compact are amortized linear even when one frame
    # spans many reads (bytes concatenation would go quadratic there)
    pending = bytearray()
    record_start = -1  # offset of the current record's first frame
    stream = create_stream_for_read(uri)
    try:
        while True:
            data = stream.read(4 << 20)
            if not data:
                break
            pending += data
            off = 0
            while off + 8 <= len(pending):
                magic, lrec = struct.unpack_from("<II", pending, off)
                check(magic == RECORDIO_MAGIC, "Invalid RecordIO format")
                cflag = decode_flag(lrec)
                frame = 8 + ((decode_length(lrec) + 3) & ~3)
                if off + frame > len(pending):
                    break
                if cflag in (0, 1):
                    check(record_start < 0, "Invalid RecordIO format")
                    record_start = pos + off
                else:
                    check(record_start >= 0, "Invalid RecordIO format")
                if cflag in (0, 3):
                    entries.append((len(entries), record_start))
                    record_start = -1
                off += frame
            pos += off
            del pending[:off]
        check(not pending and record_start < 0,
              "truncated RecordIO file: trailing partial record")
    finally:
        stream.close()
    with create_stream(index_uri, "w") as out:
        out.write(
            "".join(f"{k}\t{offset}\n" for k, offset in entries).encode()
        )
    return len(entries)
