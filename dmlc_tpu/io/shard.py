"""Pre-tokenized columnar shards: bake once, ingest at RecordIO speed.

Text ingest pays the tokenize/strtonum tax every epoch: parse_only peaks
near ~1 GB/s while the RecordIO framed path ingests at ~2.4 GB/s
(BENCH_r05). A *shard* is the dataset with that tax paid once, offline:
the parser's :class:`~dmlc_tpu.data.row_block.RowBlockContainer` columnar
arrays written to disk as typed segments, so epoch-1+ reads are
``np.frombuffer`` slices (zero-copy off an mmap) instead of text parses.
This evolves the reference's ``indexed_recordio_split`` idea (random
access via a record index) from framed-bytes to columnar-typed storage.

File layout (all little-endian)::

    MAGIC "DTSHARD1"                                      8 bytes
    header <HHI>: version, reserved, rows_per_window      8 bytes
    window 0                                              |
      <BBHIQ>: tag 'W', flags, reserved, nrows, nnz       | data
      label    f32[nrows]                                 |
      weight   f32[nrows]      (flags & HAS_WEIGHT)       |
      qid      i64[nrows]      (flags & HAS_QID)          |
      row_nnz  u32[nrows]                                 |
      index    u32[nnz]                                   |
      value    f32[nnz]        (flags & HAS_VALUE)        |
      field    u32[nnz]        (flags & HAS_FIELD)        |
    window 1 ... window N-1                               |
    footer                                                |
      index    <QQQI>[N]: offset, nbytes, nnz, nrows      | 28 B each
      meta     <QQIHH>: rows, nnz, nwindows, ver, flags   | 24 B
    tail <IQ>: crc32(footer), footer_len                  12 bytes
    MAGIC "DTSHARD1"                                      8 bytes

The footer is the random-access index: window ``i`` lives at
``offset[i]`` and is decodable in isolation, which is what the windowed
global shuffle permutes and what the determinism auditor digests
(io_read = raw window bytes, parse = decoded block — the same two
chain stages the text pipeline records). The crc32 + trailing magic
guard torn writes: a truncated or overwritten file fails closed with a
:class:`DMLCError` before any row is emitted, and the ``shard.read``
faultpoint injects exactly that class of fault for the chaos suite.

Shuffle (``DMLC_TPU_SHUFFLE`` seed, ``DMLC_TPU_SHUFFLE_WINDOW`` unit)
permutes the *global* window table — all windows of all files, before
partitioning — with a splitmix64-mixed per-epoch seed, then hands rank
``k`` of ``n`` its contiguous slice of the permuted order. The order is
a pure function of (seed, epoch), never of the world size, so
``reset_partition`` re-sharding and dispatcher redelivery replay
bit-identically: the union of every rank's slice is the one global
permutation. See docs/pipeline.md "Baked shards & global shuffle".
"""

from __future__ import annotations

import mmap
import os
import struct
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from dmlc_tpu import obs
from dmlc_tpu.data.row_block import (
    INDEX_DTYPE,
    REAL_DTYPE,
    RowBlock,
    RowBlockContainer,
)
from dmlc_tpu.utils.logging import DMLCError, check

MAGIC = b"DTSHARD1"
SHARD_FORMAT_VERSION = 1
SHARD_SUFFIX = ".dtsh"
DEFAULT_ROWS_PER_WINDOW = 4096

_HEADER = struct.Struct("<HHI")  # version, reserved, rows_per_window
_WIN = struct.Struct("<BBHIQ")  # tag, flags, reserved, nrows, nnz
_IDX = struct.Struct("<QQQI")  # offset, nbytes, nnz, nrows
_META = struct.Struct("<QQIHH")  # rows, nnz, nwindows, version, flags
_TAIL = struct.Struct("<IQ")  # crc32(footer), footer_len

_WIN_TAG = 0x57  # 'W'
HAS_WEIGHT = 1
HAS_QID = 2
HAS_VALUE = 4
HAS_FIELD = 8

# numpy view of the footer index: one structured record per window
_IDX_DTYPE = np.dtype(
    [("offset", "<u8"), ("nbytes", "<u8"), ("nnz", "<u8"), ("nrows", "<u4")]
)


def _local_path(uri: str) -> str:
    """Strip the ``file://`` scheme; shards are a local-filesystem format
    (the bake CLI writes them next to the corpus; remote serving goes
    through the data service, whose workers read locally)."""
    if uri.startswith("file://"):
        return uri[len("file://"):]
    return uri


def is_shard_uri(uri: str) -> bool:
    """Whether ``uri`` names baked shard data by suffix convention."""
    return _local_path(str(uri)).split("?", 1)[0].endswith(SHARD_SUFFIX)


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------


class ShardWriter:
    """Stream RowBlocks into one ``.dtsh`` shard file.

    Rows are re-windowed to ``rows_per_window`` regardless of how the
    incoming blocks were chunked (the window is the shuffle/audit/index
    granule, so its size must be a bake parameter, not an accident of
    parser chunking). ``close`` seals the footer; an unclosed or
    interrupted write leaves a file with no valid tail, which readers
    reject — torn bakes fail closed.
    """

    def __init__(self, path: str, rows_per_window: int = DEFAULT_ROWS_PER_WINDOW):
        self.path = _local_path(path)
        self.rows_per_window = max(1, int(rows_per_window))
        self._file = open(self.path, "wb")
        self._file.write(MAGIC)
        self._file.write(_HEADER.pack(SHARD_FORMAT_VERSION, 0, self.rows_per_window))
        self._index: List[Tuple[int, int, int, int]] = []
        self._pending = RowBlockContainer()
        self._union_flags = 0
        self.rows_written = 0
        self.nnz_written = 0
        self._closed = False

    def write_block(self, block) -> None:
        """Append a RowBlock (or anything with ``to_block``)."""
        if hasattr(block, "to_block") and not isinstance(block, RowBlock):
            block = block.to_block()
        self._pending.push_block(block)
        while self._pending.size >= self.rows_per_window:
            whole = self._pending.to_block()
            n = len(whole)
            w = self.rows_per_window
            full = (n // w) * w
            for lo in range(0, full, w):
                self._emit_window(whole.slice(lo, lo + w))
            self._pending = RowBlockContainer()
            if full < n:
                self._pending.push_block(whole.slice(full, n))

    def _emit_window(self, block: RowBlock) -> None:
        nrows = len(block)
        nnz = block.num_nonzero
        flags = 0
        segs: List[np.ndarray] = [np.ascontiguousarray(block.label, dtype=REAL_DTYPE)]
        if block.weight is not None:
            flags |= HAS_WEIGHT
            segs.append(np.ascontiguousarray(block.weight, dtype=REAL_DTYPE))
        if block.qid is not None:
            flags |= HAS_QID
            segs.append(np.ascontiguousarray(block.qid, dtype=np.int64))
        segs.append(np.ascontiguousarray(np.diff(block.offset), dtype=np.uint32))
        segs.append(np.ascontiguousarray(block.index, dtype=np.uint32))
        if block.value is not None:
            flags |= HAS_VALUE
            segs.append(np.ascontiguousarray(block.value, dtype=REAL_DTYPE))
        if block.field is not None:
            flags |= HAS_FIELD
            segs.append(np.ascontiguousarray(block.field, dtype=np.uint32))
        offset = self._file.tell()
        self._file.write(_WIN.pack(_WIN_TAG, flags, 0, nrows, nnz))
        for seg in segs:
            self._file.write(seg.tobytes())
        nbytes = self._file.tell() - offset
        self._index.append((offset, nbytes, nnz, nrows))
        self._union_flags |= flags
        self.rows_written += nrows
        self.nnz_written += nnz

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._pending.size:
            self._emit_window(self._pending.to_block())
            self._pending = RowBlockContainer()
        footer = b"".join(_IDX.pack(*entry) for entry in self._index)
        footer += _META.pack(
            self.rows_written,
            self.nnz_written,
            len(self._index),
            SHARD_FORMAT_VERSION,
            self._union_flags,
        )
        self._file.write(footer)
        self._file.write(_TAIL.pack(zlib.crc32(footer) & 0xFFFFFFFF, len(footer)))
        self._file.write(MAGIC)
        self._file.close()

    def __enter__(self) -> "ShardWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# Reader
# ---------------------------------------------------------------------------


class ShardReader:
    """Random-access window reads over one sealed shard file.

    ``use_mmap`` (default: the ``DMLC_TPU_SHARD_MMAP`` knob) maps the
    file once and decodes windows as zero-copy ``np.frombuffer`` views;
    the fallback path seeks and reads per window. Both verify the
    leading magic and the crc32-guarded footer before the first row is
    served, and both cross-check every window header against the footer
    index — a torn footer, truncated segment, or stale index raises
    :class:`DMLCError` rather than yielding silently wrong rows.
    """

    def __init__(self, path: str, use_mmap: Optional[bool] = None):
        from dmlc_tpu.params.knobs import shard_mmap

        self.path = _local_path(path)
        self._mmap_wanted = shard_mmap() if use_mmap is None else bool(use_mmap)
        self._file = open(self.path, "rb")
        self._mm: Optional[mmap.mmap] = None
        self._load_footer()
        if self._mmap_wanted:
            try:
                self._mm = mmap.mmap(self._file.fileno(), 0, access=mmap.ACCESS_READ)
            except (ValueError, OSError):  # empty or unmappable: seek path
                self._mm = None

    # ---- footer ---------------------------------------------------------
    def _fail(self, why: str) -> None:
        raise DMLCError("bad shard %s: %s" % (self.path, why))

    def _load_footer(self) -> None:
        from dmlc_tpu.resilience import faultpoint

        # chaos-suite hook: an injected fault here behaves like a transient
        # read error (OSError → retried per RetryPolicy); real corruption
        # below raises DMLCError, which is fatal by classification
        faultpoint("shard.read")
        size = os.fstat(self._file.fileno()).st_size
        head_len = len(MAGIC) + _HEADER.size
        tail_len = _TAIL.size + len(MAGIC)
        if size < head_len + tail_len:
            self._fail("file too short (%d bytes)" % size)
        self._file.seek(0)
        if self._file.read(len(MAGIC)) != MAGIC:
            self._fail("leading magic mismatch")
        version, _, self.rows_per_window = _HEADER.unpack(
            self._file.read(_HEADER.size))
        if version != SHARD_FORMAT_VERSION:
            self._fail("unsupported version %d" % version)
        self._file.seek(size - tail_len)
        crc, footer_len = _TAIL.unpack(self._file.read(_TAIL.size))
        if self._file.read(len(MAGIC)) != MAGIC:
            self._fail("trailing magic mismatch (torn or unsealed write)")
        if footer_len > size - head_len - tail_len:
            self._fail("footer length %d exceeds file" % footer_len)
        self._file.seek(size - tail_len - footer_len)
        footer = self._file.read(footer_len)
        if (zlib.crc32(footer) & 0xFFFFFFFF) != crc:
            self._fail("footer crc mismatch (torn write)")
        if (footer_len - _META.size) % _IDX.size:
            self._fail("footer size %d not index-aligned" % footer_len)
        (self.num_rows, self.num_nonzero, nwin, meta_ver, self.union_flags
         ) = _META.unpack(footer[footer_len - _META.size:])
        if meta_ver != version:
            self._fail("meta/header version mismatch")
        if nwin != (footer_len - _META.size) // _IDX.size:
            self._fail("window count disagrees with index size")
        self._index = np.frombuffer(footer, dtype=_IDX_DTYPE, count=nwin)
        self.footer_crc = int(crc)
        data_end = size - tail_len - footer_len
        if nwin:
            last = self._index[nwin - 1]
            if int(last["offset"]) + int(last["nbytes"]) != data_end:
                self._fail("index does not cover the data section")

    @property
    def num_windows(self) -> int:
        return len(self._index)

    def window_rows(self, i: int) -> int:
        return int(self._index[i]["nrows"])

    def window_nbytes(self, i: int) -> int:
        return int(self._index[i]["nbytes"])

    # ---- window reads ---------------------------------------------------
    def window_bytes(self, i: int):
        """Raw encoded bytes of window ``i`` — a zero-copy memoryview in
        mmap mode. This is what the audit plane's io_read digest covers."""
        from dmlc_tpu.resilience import faultpoint

        faultpoint("shard.read")
        ent = self._index[i]
        off, n = int(ent["offset"]), int(ent["nbytes"])
        if self._mm is not None:
            return memoryview(self._mm)[off:off + n]
        self._file.seek(off)
        buf = self._file.read(n)
        if len(buf) != n:
            self._fail("truncated window %d (%d of %d bytes)" % (i, len(buf), n))
        return buf

    def read_window(self, i: int, raw=None) -> RowBlock:
        """Decode window ``i`` into a RowBlock. Pass ``raw`` (from
        :meth:`window_bytes`) to decode an already-fetched buffer."""
        ent = self._index[i]
        if raw is None:
            raw = self.window_bytes(i)
        tag, flags, _, nrows, nnz = _WIN.unpack_from(raw, 0)
        if tag != _WIN_TAG:
            self._fail("window %d tag %#x (index/data skew)" % (i, tag))
        if nrows != int(ent["nrows"]) or nnz != int(ent["nnz"]):
            self._fail("window %d header disagrees with footer index" % i)
        pos = _WIN.size
        need = _WIN.size + 8 * nrows + 4 * nnz  # label + row_nnz + index
        if flags & HAS_WEIGHT:
            need += 4 * nrows
        if flags & HAS_QID:
            need += 8 * nrows
        if flags & HAS_VALUE:
            need += 4 * nnz
        if flags & HAS_FIELD:
            need += 4 * nnz
        if len(raw) != need:
            self._fail("window %d is %d bytes, segments need %d (truncated)"
                       % (i, len(raw), need))

        def seg(dtype, count):
            nonlocal pos
            a = np.frombuffer(raw, dtype=dtype, count=count, offset=pos)
            pos += a.nbytes
            return a

        label = seg(REAL_DTYPE, nrows)
        weight = seg(REAL_DTYPE, nrows) if flags & HAS_WEIGHT else None
        qid = seg(np.int64, nrows) if flags & HAS_QID else None
        row_nnz = seg(np.uint32, nrows)
        index = seg(INDEX_DTYPE, nnz)
        value = seg(REAL_DTYPE, nnz) if flags & HAS_VALUE else None
        field = seg(np.uint32, nnz) if flags & HAS_FIELD else None
        offset = np.zeros(nrows + 1, dtype=np.int64)
        np.cumsum(row_nnz, out=offset[1:])
        if int(offset[-1]) != nnz:
            self._fail("window %d row_nnz sums to %d, header says %d"
                       % (i, int(offset[-1]), nnz))
        return RowBlock(offset=offset, label=label, index=index,
                        value=value, weight=weight, qid=qid, field=field)

    def close(self) -> None:
        if self._mm is not None:
            try:
                self._mm.close()
            except BufferError:
                pass  # exported zero-copy views keep the map alive until GC
            self._mm = None
        try:
            self._file.close()
        except Exception:
            pass

    def __enter__(self) -> "ShardReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# Parser: shard files → RowBlocks with windowed global shuffle
# ---------------------------------------------------------------------------


def _epoch_mixed_seed(seed: int, epoch: int) -> int:
    # splitmix64 decorrelation, shared with the text path's per-epoch
    # chunk shuffle so both stacks draw epochs the same way
    from dmlc_tpu.data.parsers import _mix_epoch_seed

    return _mix_epoch_seed(seed, epoch)


class ShardParser:
    """Parser-shaped reader over baked shards (one file, a directory, or
    a ``part-*`` family — whatever :func:`list_split_files` resolves).

    The unit of delivery is the baked window: ``next_block`` returns one
    window per call, decoded zero-copy in mmap mode, with the same
    io_read/parse span + flow-id + audit-digest wiring the text
    pipeline's :class:`~dmlc_tpu.data.pipeline.PipelinedParser` gives
    chunks, so everything downstream (DeviceFeed, BlockService, the
    audit plane) is format-blind.

    Shuffle: a seed ≥ 0 (``shuffle_chunks`` URI arg, else the
    ``DMLC_TPU_SHUFFLE`` knob) arms a seeded permutation of the global
    window table in units of ``DMLC_TPU_SHUFFLE_WINDOW`` consecutive
    windows. The permutation is a pure function of (seed, epoch):
    construction is epoch 0, each ``before_first`` advances one epoch,
    and ``reset_partition`` re-slices the *current* epoch's order — so
    any (rank, world) decomposition of the same seed reads the same
    global sequence, which is what makes dispatcher redelivery and
    mid-epoch resume bit-reproducible with shuffle armed.

    Audit: with shuffle armed the auditor's shard signature is salted
    with the epoch-mixed seed. Delivery order then legitimately differs
    across epochs, and the signature change scopes chain comparison to
    one epoch (cross-rank and restart-replay compares still line up —
    same seed + epoch ⇒ same salt) instead of tripping the epoch-roll
    self-check.
    """

    def __init__(
        self,
        uri: str,
        part_index: int = 0,
        num_parts: int = 1,
        args: Optional[Dict] = None,
        nthread: Optional[int] = None,
        seed: Optional[int] = None,
        shuffle_window: Optional[int] = None,
        use_mmap: Optional[bool] = None,
    ):
        from dmlc_tpu.io.filesystem import list_split_files
        from dmlc_tpu.params import knobs

        del nthread  # decode is frombuffer slices; prefetch happens above us
        self.uri = str(uri)
        args = dict(args or {})
        if seed is None:
            raw = args.get("shuffle_chunks")
            seed = int(raw) if raw is not None else knobs.shuffle_seed()
        self._seed = int(seed)
        self._unit = max(
            1,
            int(shuffle_window) if shuffle_window is not None
            else knobs.shuffle_window(),
        )
        infos = list_split_files(self.uri)
        check(bool(infos), "shard uri %s matches no files", self.uri)
        for info in infos:
            check(info.path.protocol in ("file://", ""),
                  "shard reader requires local files, got %s",
                  info.path.protocol)
        paths = sorted(info.path.name for info in infos)
        self._readers = [ShardReader(p, use_mmap=use_mmap) for p in paths]
        # global window table, in (file, window) order: the domain the
        # shuffle permutes and the partitioner slices
        self._table: List[Tuple[int, int]] = [
            (f, w)
            for f, rd in enumerate(self._readers)
            for w in range(rd.num_windows)
        ]
        self.num_rows = sum(rd.num_rows for rd in self._readers)
        self._part = int(part_index)
        self._nparts = max(1, int(num_parts))
        self._epoch = 0
        self._seq = 0
        self._epoch_base = 0
        from dmlc_tpu.obs import audit

        self._audit = audit.auditor()
        self.bytes_read = 0
        self._order: np.ndarray = np.empty(0, dtype=np.int64)
        self._pos = 0
        self._closed = False
        self._reorder()

    # ---- order ----------------------------------------------------------
    def _global_order(self) -> np.ndarray:
        nwin = len(self._table)
        if self._seed < 0 or nwin == 0:
            return np.arange(nwin, dtype=np.int64)
        mixed = _epoch_mixed_seed(self._seed, self._epoch)
        rng = np.random.Generator(np.random.PCG64(mixed))
        nunits = -(-nwin // self._unit)
        perm = rng.permutation(nunits)
        starts = perm * self._unit
        order = np.concatenate([
            np.arange(s, min(s + self._unit, nwin), dtype=np.int64)
            for s in starts
        ]) if nunits else np.empty(0, dtype=np.int64)
        return order

    def _reorder(self) -> None:
        order = self._global_order()
        lo = self._part * len(order) // self._nparts
        hi = (self._part + 1) * len(order) // self._nparts
        self._order = order[lo:hi]
        self._pos = 0
        self._stamp_audit()

    def _stamp_audit(self) -> None:
        if not self._audit.enabled:
            return
        sig_uri = self.uri
        if self._seed >= 0:
            # per-epoch salt: a reshuffled epoch is a different read plan,
            # so it gets its own chain domain (see class docstring)
            sig_uri = "%s#shuffle-%x" % (
                self.uri, _epoch_mixed_seed(self._seed, self._epoch))
        self._audit.set_shard(sig_uri, self._part, self._nparts)

    # ---- Parser surface -------------------------------------------------
    def next_block(self) -> Optional[RowBlock]:
        from dmlc_tpu.resilience import faultpoint

        check(not self._closed, "shard parser is closed")
        if self._pos >= len(self._order):
            return None
        fidx, widx = self._table[int(self._order[self._pos])]
        reader = self._readers[fidx]
        seq = self._seq
        fid = obs.new_flow()
        with obs.span("io_read", chunk=seq, flow=fid):
            raw = reader.window_bytes(widx)
            obs.flow_start(fid, "chunk")
        if self._audit.enabled:
            self._audit.note_chunk(seq - self._epoch_base, raw)
        with obs.span("parse", chunk=seq, flow=fid):
            obs.flow_step(fid, "chunk")
            faultpoint("shard.read")
            block = reader.read_window(widx, raw)
        if self._audit.enabled:
            self._audit.note_parse(seq - self._epoch_base, block)
        block.flow_id = fid
        self.bytes_read += len(raw)
        self._seq += 1
        self._pos += 1
        return block

    def __iter__(self) -> Iterator[RowBlock]:
        while True:
            block = self.next_block()
            if block is None:
                return
            yield block

    def before_first(self) -> None:
        """Rewind for the next epoch: with shuffle armed this draws the
        next epoch's permutation (construction was epoch 0)."""
        self._epoch += 1
        self._epoch_base = self._seq
        self._reorder()

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        """Re-shard within the *current* epoch's global order (elastic
        re-sharding composes with shuffle: the permutation is fixed by
        (seed, epoch), only the slice moves)."""
        self._part = int(part_index)
        self._nparts = max(1, int(num_parts))
        self._reorder()

    def stats(self) -> dict:
        return {
            "windows": len(self._order),
            "windows_total": len(self._table),
            "files": len(self._readers),
            "rows": int(self.num_rows),
            "epoch": int(self._epoch),
            "shuffle_seed": int(self._seed),
            "shuffle_window": int(self._unit),
        }

    # ---- job-snapshot state ---------------------------------------------
    def snapshot_state(self) -> dict:
        """Resumable read-plan state for a job snapshot: everything the
        permutation is a pure function of. The order itself is *not*
        serialized — resume re-derives it from (seed, epoch) and
        re-slices for the current partition, so the snapshot stays tiny
        and a restore is provably the same plan, not a copied one."""
        return {
            "uri": self.uri,
            "seed": int(self._seed),
            "window": int(self._unit),
            "epoch": int(self._epoch),
            "part": int(self._part),
            "nparts": int(self._nparts),
        }

    def restore_state(self, st: dict) -> None:
        """Jump to the epoch boundary *after* ``st["epoch"]`` (snapshots
        are taken at epoch boundaries: the snapshotted epoch finished, so
        the resumed run starts the next one). Re-derives the epoch
        permutation from the restored (seed, epoch) and re-slices it for
        this parser's *current* partition — resuming with a different
        part/nparts split composes the same way elastic re-sharding
        does."""
        check(st.get("uri", self.uri) == self.uri,
              "snapshot read-plan is for %s, not %s",
              st.get("uri"), self.uri)
        check(int(st.get("window", self._unit)) == self._unit,
              "snapshot shuffle window %s != configured %d (the epoch "
              "permutation would differ)", st.get("window"), self._unit)
        self._seed = int(st["seed"])
        self._epoch = int(st["epoch"]) + 1
        self._epoch_base = self._seq
        self._reorder()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for rd in self._readers:
            rd.close()

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Source-cache keying
# ---------------------------------------------------------------------------


def cache_token(uri: str, data_format: str) -> Optional[List]:
    """Shard-content token folded into SourceCache.chunk_key.

    Text sources are keyed by (uri, part, nparts, format) alone; baked
    shards add [format version, per-file (footer crc32, size), shuffle
    seed, shuffle window] so a re-baked file (same path, new bytes) or a
    re-seeded job never hits another job's cached parse. Returns None
    for non-shard inputs (key unchanged), and degrades to (size, mtime)
    when a footer is unreadable — an unreadable shard must still never
    collide with its replacement."""
    if data_format != "shard" and not is_shard_uri(uri):
        return None
    from dmlc_tpu.params import knobs

    token: List = [SHARD_FORMAT_VERSION, knobs.shuffle_seed(),
                   knobs.shuffle_window()]
    files: List = []
    try:
        from dmlc_tpu.io.filesystem import list_split_files

        for info in sorted(list_split_files(uri), key=lambda i: i.path.name):
            path = info.path.name
            try:
                size = os.path.getsize(path)
                with open(path, "rb") as f:
                    f.seek(max(0, size - _TAIL.size - len(MAGIC)))
                    crc = _TAIL.unpack(f.read(_TAIL.size))[0]
                files.append([path, int(size), int(crc)])
            except (OSError, struct.error):
                try:
                    st = os.stat(path)
                    files.append([path, int(st.st_size), int(st.st_mtime_ns)])
                except OSError:
                    files.append([path, -1, -1])
    except Exception:
        files.append(["unlistable", str(uri)])
    token.append(files)
    return token
