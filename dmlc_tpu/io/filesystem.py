"""FileSystem plugin interface + URI-dispatched stream factories.

Capability parity: ``dmlc::io::URI`` {protocol,host,name} parsing
(src/io/filesys.h:18-52), ``FileInfo`` (filesys.h:63), the abstract
``FileSystem`` (GetPathInfo/ListDirectory/Open/OpenForRead, filesys.h:75-125)
with default recursive listing (src/io/filesys.cc), the protocol→singleton
dispatch of ``src/io.cc:31-72``, ``Stream::Create`` (src/io.cc:133-139) with
stdin/stdout support (src/io/local_filesys.cc:144-151), and the reference's
plugin backends: local FS, HTTP read (the reference's HttpReadStream,
s3_filesys.cc:539-555). A MemoryFileSystem ("mem://") is TPU-new: the
in-process fake FS the reference lacks (SURVEY §4). GCS and S3 (the
reference's S3 client role) live in dmlc_tpu.io.object_store, lazily
imported and self-registered for gs:// gcs:// s3://.
"""

from __future__ import annotations

import io as _pyio
import os
import re
import stat as _stat
import sys
import threading
from dataclasses import dataclass, field as _dc_field
from typing import Callable, Dict, List, Optional

from dmlc_tpu.io.stream import FileObjStream, SeekStream, Stream
from dmlc_tpu.utils.logging import DMLCError, check


@dataclass
class URI:
    """Parsed URI {protocol, host, name} (filesys.h:18-52).

    ``file:///a/b`` → protocol="file://", host="", name="/a/b";
    plain paths get protocol "file://" implicitly (src/io.cc:33-35).
    """

    protocol: str = ""
    host: str = ""
    name: str = ""

    @classmethod
    def parse(cls, uri: str) -> "URI":
        pos = uri.find("://")
        if pos < 0:
            return cls(protocol="file://", host="", name=uri)
        protocol = uri[: pos + 3]
        rest = uri[pos + 3 :]
        slash = rest.find("/")
        if slash < 0:
            return cls(protocol=protocol, host=rest, name="/")
        return cls(protocol=protocol, host=rest[:slash], name=rest[slash:])

    def str_full(self) -> str:
        if self.protocol == "file://" and not self.host:
            return self.name
        return f"{self.protocol}{self.host}{self.name}"


FILE_TYPE_FILE = 0
FILE_TYPE_DIR = 1


@dataclass
class FileInfo:
    """Stat result (filesys.h:63-72)."""

    path: URI = _dc_field(default_factory=URI)
    size: int = 0
    type: int = FILE_TYPE_FILE


class FileSystem:
    """Abstract filesystem plugin (filesys.h:75-125)."""

    def get_path_info(self, path: URI) -> FileInfo:
        raise NotImplementedError

    def list_directory(self, path: URI) -> List[FileInfo]:
        raise NotImplementedError

    def open(self, path: URI, flag: str) -> Stream:
        """flag ∈ {"r", "w", "a"} binary."""
        raise NotImplementedError

    def open_for_read(self, path: URI, allow_null: bool = False) -> Optional[SeekStream]:
        raise NotImplementedError

    def list_directory_recursive(self, path: URI) -> List[FileInfo]:
        """Default recursion over list_directory (src/io/filesys.cc)."""
        out: List[FileInfo] = []
        stack = [path]
        while stack:
            cur = stack.pop()
            for info in self.list_directory(cur):
                if info.type == FILE_TYPE_DIR:
                    stack.append(info.path)
                else:
                    out.append(info)
        return out

    def delete(self, path: URI) -> None:
        """Remove one object/file (not part of the reference surface — its
        cache/checkpoint files were cleaned out-of-band; the checkpoint
        manager needs pruning in-band)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support delete"
        )

    def exists(self, path: URI) -> bool:
        try:
            self.get_path_info(path)
            return True
        except (FileNotFoundError, DMLCError, OSError):
            return False

    def read_range(
        self, path: URI, offset: int, length: int, cancelled=None
    ) -> bytes:
        """Read up to ``length`` bytes at ``offset`` (short only at EOF).

        The primitive under parallel range-GET readahead (io/readahead.py).
        Default: seek+read on a fresh stream; remote backends override with
        one bounded range request per call so N calls = N independent
        connections (the multi-connection generalization of the reference's
        single reconnecting range-GET stream, s3_filesys.cc:219-445).
        ``cancelled()`` lets long retry loops stop early on teardown;
        the local default has no retry loop and ignores it.
        """
        stream = self.open_for_read(path)
        try:
            stream.seek(offset)
            out = bytearray()
            while len(out) < length:
                chunk = stream.read(length - len(out))
                if not chunk:
                    break
                out.extend(chunk)
            return bytes(out)
        finally:
            stream.close()


# ---------------------------------------------------------------------------
# Local filesystem (src/io/local_filesys.{h,cc})
# ---------------------------------------------------------------------------


class LocalFileSystem(FileSystem):
    def get_path_info(self, path: URI) -> FileInfo:
        st = os.stat(path.name)
        ftype = FILE_TYPE_DIR if _stat.S_ISDIR(st.st_mode) else FILE_TYPE_FILE
        return FileInfo(path=path, size=st.st_size, type=ftype)

    def list_directory(self, path: URI) -> List[FileInfo]:
        out = []
        for entry in sorted(os.listdir(path.name)):
            full = os.path.join(path.name, entry)
            sub = URI(protocol=path.protocol, host=path.host, name=full)
            out.append(self.get_path_info(sub))
        return out

    def open(self, path: URI, flag: str) -> Stream:
        check(flag in ("r", "w", "a"), "invalid open flag %s", flag)
        if path.name == "stdin":
            return FileObjStream(sys.stdin.buffer, seekable=False)
        if path.name == "stdout":
            return FileObjStream(sys.stdout.buffer, seekable=False)
        return FileObjStream(open(path.name, flag + "b"))

    def open_for_read(self, path: URI, allow_null: bool = False) -> Optional[SeekStream]:
        try:
            return FileObjStream(open(path.name, "rb"))
        except FileNotFoundError:
            if allow_null:
                return None
            raise

    def delete(self, path: URI) -> None:
        os.remove(path.name)


# ---------------------------------------------------------------------------
# In-memory filesystem — the hermetic fake FS for tests (TPU-new; SURVEY §4
# notes the reference has no fake backends and relied on live S3/HDFS).
# ---------------------------------------------------------------------------


class MemoryFileSystem(FileSystem):
    """Process-global "mem://host/path" filesystem backed by dicts."""

    _lock = threading.Lock()
    _files: Dict[str, bytearray] = {}

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._files.clear()

    @classmethod
    def put(cls, key: str, data: bytes) -> None:
        with cls._lock:
            cls._files[key] = bytearray(data)

    @staticmethod
    def _key(path: URI) -> str:
        return f"{path.host}{path.name}"

    def get_path_info(self, path: URI) -> FileInfo:
        key = self._key(path)
        with self._lock:
            if key in self._files:
                return FileInfo(path=path, size=len(self._files[key]), type=FILE_TYPE_FILE)
            prefix = key.rstrip("/") + "/"
            if any(k.startswith(prefix) for k in self._files):
                return FileInfo(path=path, size=0, type=FILE_TYPE_DIR)
        raise FileNotFoundError(key)

    def list_directory(self, path: URI) -> List[FileInfo]:
        key = self._key(path).rstrip("/") + "/"
        out: Dict[str, FileInfo] = {}
        with self._lock:
            for k, data in self._files.items():
                if not k.startswith(key):
                    continue
                rest = k[len(key) :]
                slash = rest.find("/")
                if slash < 0:
                    sub = URI(path.protocol, path.host, f"{path.name.rstrip('/')}/{rest}")
                    out[rest] = FileInfo(path=sub, size=len(data), type=FILE_TYPE_FILE)
                else:
                    dirname = rest[:slash]
                    sub = URI(path.protocol, path.host, f"{path.name.rstrip('/')}/{dirname}")
                    out.setdefault(dirname, FileInfo(path=sub, size=0, type=FILE_TYPE_DIR))
        return [out[k] for k in sorted(out)]

    class _MemWriteStream(Stream):
        def __init__(self, fs_files, lock, key: str, append: bool):
            self._files = fs_files
            self._lock = lock
            self._key = key
            with lock:
                if not append or key not in fs_files:
                    fs_files[key] = bytearray()
                self._buf = fs_files[key]

        def write(self, data: bytes) -> None:
            with self._lock:
                self._buf.extend(data)

        def read(self, nbytes: int) -> bytes:
            raise IOError("write-only stream")

    class _MemReadStream(SeekStream):
        def __init__(self, data: bytes):
            self._buf = _pyio.BytesIO(data)

        def read(self, nbytes: int) -> bytes:
            return self._buf.read(nbytes)

        def write(self, data: bytes) -> None:
            raise IOError("read-only stream")

        def seek(self, pos: int) -> None:
            self._buf.seek(pos)

        def tell(self) -> int:
            return self._buf.tell()

    def open(self, path: URI, flag: str) -> Stream:
        check(flag in ("r", "w", "a"), "invalid open flag %s", flag)
        key = self._key(path)
        if flag == "r":
            stream = self.open_for_read(path)
            assert stream is not None
            return stream
        return self._MemWriteStream(self._files, self._lock, key, append=(flag == "a"))

    def delete(self, path: URI) -> None:
        with self._lock:
            self._files.pop(self._key(path), None)

    def open_for_read(self, path: URI, allow_null: bool = False) -> Optional[SeekStream]:
        key = self._key(path)
        with self._lock:
            data = self._files.get(key)
        if data is None:
            if allow_null:
                return None
            raise FileNotFoundError(key)
        return self._MemReadStream(bytes(data))


# ---------------------------------------------------------------------------
# HTTP(S) read-only backend (reference HttpReadStream, s3_filesys.cc:539-555;
# registered for http:// https:// at src/io.cc:62-66).
# ---------------------------------------------------------------------------


def read_range_with_retry(
    open_ranged,
    offset: int,
    length: int,
    display: str,
    max_retry: int = 50,
    retry_sleep_s: float = 0.1,
    cancelled=None,
    into=None,
):
    """One logical bounded range read over HTTP-shaped backends, with
    per-range retry — the single copy of the remote ``read_range`` loop
    shared by the object stores and WebHDFS.

    ``open_ranged(start, end)`` must return a response object (context
    manager with ``.read`` and ``.headers``) covering bytes [start, end).
    Retries continue from the bytes already delivered (the reconnect shape
    of s3_filesys.cc:319-342). A response whose body is shorter than its
    own Content-Length is a truncated connection and retries; a clean
    response shorter than the asked range is EOF.

    Retry discipline (classification, jittered backoff, deadline, budget,
    ``dmlc_retry_*`` metrics under site ``io.read``) is delegated to
    :class:`dmlc_tpu.resilience.RetryPolicy`; this loop keeps only the
    range-specific parts: a delivered byte is progress and refills the
    attempt count (bounded by the policy's absolute ceiling), 416 means
    the offset is at/past EOF and returns empty. ``cancelled()``
    (optional) is checked between attempts so a teardown can stop a long
    retry budget promptly.
    """
    import urllib.error

    from dmlc_tpu.resilience import RetryPolicy, faultpoint

    # single preallocated buffer + readinto: the ingest hot path hands
    # every fetched byte to the native pipeline, so the fetch layer must
    # not stack per-chunk bytes + extend + final-join copies on top.
    # `into` (a writable memoryview >= length) skips even that buffer —
    # the response body lands in caller memory and the return is the count.
    if into is None:
        out = bytearray(length)
        view = memoryview(out)
    else:
        out = None
        view = into[:length]
    filled = 0
    state = RetryPolicy(max_attempts=max_retry, base_s=retry_sleep_s).start(
        "io.read", display=f"range read of {display}"
    )
    while filled < length:
        if cancelled is not None and cancelled():
            raise DMLCError(f"range read of {display} cancelled")
        want = length - filled
        got = 0  # bytes this attempt delivered (read in the except path)
        try:
            faultpoint("io.read")
            with open_ranged(offset + filled, offset + length) as resp:
                header = resp.headers.get("Content-Length")
                expected = int(header) if header is not None else None
                readinto = getattr(resp, "readinto", None)
                # `filled` advances as bytes land so a truncated response
                # keeps its partial progress across the retry (the
                # reconnect-from-where-we-stopped shape of
                # s3_filesys.cc:319-342)
                while got < want:
                    if readinto is not None:
                        n = readinto(view[filled : filled + (want - got)])
                        if not n:
                            break
                        got += n
                        filled += n
                    else:  # duck-typed responses without readinto
                        chunk = resp.read(want - got)
                        if not chunk:
                            break
                        view[filled : filled + len(chunk)] = chunk
                        got += len(chunk)
                        filled += len(chunk)
                if expected is not None and got < min(expected, want):
                    # server promised more than it sent: dropped connection,
                    # NOT end-of-object (HTTPResponse.read returns short
                    # instead of raising when the peer closes mid-body)
                    raise OSError(
                        f"truncated response: {got} of {expected} bytes"
                    )
            if filled < length and got < want:
                break  # clean short bounded response: range hit EOF
        except Exception as err:  # noqa: BLE001 — the policy classifies
            if isinstance(err, urllib.error.HTTPError) and err.code == 416:
                err.close()  # offset at/past EOF: empty range
                break
            # a connection that delivered bytes before dropping made
            # progress, not a stall — refill the attempt count (the policy
            # caps total attempts so a byte-dripping server still bounds)
            state.failed(err, progressed=got > 0)
    if into is not None:
        return filled
    if filled == length:
        return out  # bytes-like; no final copy on the full-range hot path
    return bytes(view[:filled])


class RangedReadStream(SeekStream):
    """Lazy-seek reconnecting range-GET reader — the CURLReadStreamBase
    shape (s3_filesys.cc:219-445): seek only stores the offset; a connection
    opens at first read from that offset; short reads AND reconnect failures
    both retry (≤ ``max_retry`` with ``retry_sleep_s`` backoff, mirroring
    the reference's ≤50×100ms loop at s3_filesys.cc:319-342).

    ``open_ranged(start) -> readable response`` is the backend hook; used by
    HTTPFileSystem and both object-store backends.
    """

    def __init__(self, open_ranged, size: int, display: str,
                 max_retry: int = 50, retry_sleep_s: float = 0.1):
        self._open_ranged = open_ranged
        self._size = size
        self._display = display
        self._max_retry = max_retry
        self._retry_sleep_s = retry_sleep_s
        self._pos = 0
        self._resp = None
        self._resp_pos = -1

    def seek(self, pos: int) -> None:
        check(0 <= pos <= self._size, "seek out of range: %d", pos)
        self._pos = pos  # lazy: next read reconnects with Range

    def tell(self) -> int:
        return self._pos

    def write(self, data: bytes) -> None:
        raise IOError("read-only stream")

    def _drop(self) -> None:
        if self._resp is not None:
            try:
                self._resp.close()
            except Exception:
                pass
            self._resp = None

    def read(self, nbytes: int) -> bytes:
        from dmlc_tpu.resilience import RetryPolicy, faultpoint

        if self._pos >= self._size:
            return b""
        nbytes = min(nbytes, self._size - self._pos)
        out = bytearray()
        state = RetryPolicy(
            max_attempts=self._max_retry, base_s=self._retry_sleep_s
        ).start("io.read", display=f"reconnecting read of {self._display}")
        progressed = False
        last_err: Optional[Exception] = None
        while len(out) < nbytes:
            try:
                faultpoint("io.read")
                if self._resp is None or self._resp_pos != self._pos:
                    self._drop()
                    self._resp = self._open_ranged(self._pos)
                    self._resp_pos = self._pos
                chunk = self._resp.read(nbytes - len(out))
            except Exception as err:  # noqa: BLE001 — the policy classifies
                last_err = err
                chunk = b""
            if chunk:
                out.extend(chunk)
                self._pos += len(chunk)
                self._resp_pos = self._pos
                progressed = True
            else:
                self._drop()
                # a mid-body peer close surfaces as an empty read, not an
                # exception — synthesize a transient error for the policy
                state.failed(
                    last_err if last_err is not None
                    else OSError("connection closed mid-read"),
                    progressed=progressed,
                )
                progressed = False
                last_err = None
        return bytes(out)

    def close(self) -> None:
        self._drop()


class HTTPFileSystem(FileSystem):
    """Read-only; supports range reads when the server does."""

    def _url(self, path: URI) -> str:
        return f"{path.protocol}{path.host}{path.name}"

    def get_path_info(self, path: URI) -> FileInfo:
        import urllib.request

        req = urllib.request.Request(self._url(path), method="HEAD")
        with urllib.request.urlopen(req, timeout=30) as resp:
            size = int(resp.headers.get("Content-Length", 0))
        return FileInfo(path=path, size=size, type=FILE_TYPE_FILE)

    def list_directory(self, path: URI) -> List[FileInfo]:
        raise DMLCError("HTTP filesystem does not support listing")

    def open(self, path: URI, flag: str) -> Stream:
        check(flag == "r", "HTTP filesystem is read-only")
        stream = self.open_for_read(path)
        assert stream is not None
        return stream

    def open_for_read(self, path: URI, allow_null: bool = False) -> Optional[SeekStream]:
        try:
            size = self.get_path_info(path).size
        except Exception:
            if allow_null:
                return None
            raise
        url = self._url(path)

        def open_ranged(start: int):
            import urllib.request

            req = urllib.request.Request(url)
            if start > 0:
                req.add_header("Range", f"bytes={start}-")
            return urllib.request.urlopen(req, timeout=60)

        return RangedReadStream(open_ranged, size, url)


# ---------------------------------------------------------------------------
# Protocol registry + factories (src/io.cc:31-72,133-139)
# ---------------------------------------------------------------------------

_fs_factories: Dict[str, Callable[[URI], FileSystem]] = {}
# instances cache per (protocol, host): hdfs:// instances are bound to
# their namenode (the reference refcounts per-namenode hdfsFS connections,
# hdfs_filesys.cc:93-125); object stores ignore the host at construction
_fs_instances: Dict[tuple, FileSystem] = {}
_fs_lock = threading.Lock()


def register_filesystem(protocol: str, factory: Callable[[URI], FileSystem]) -> None:
    """Register a backend for ``protocol`` (e.g. "gs://"). Mirrors the
    compile-gated dispatch table of src/io.cc:31-72, but open for plugins."""
    with _fs_lock:
        _fs_factories[protocol] = factory
        for key in [k for k in _fs_instances if k[0] == protocol]:
            _fs_instances.pop(key, None)


def get_filesystem(path: URI) -> FileSystem:
    proto = path.protocol
    if proto in ("s3://", "gs://", "gcs://") and proto not in _fs_factories:
        import dmlc_tpu.io.object_store  # noqa: F401  (self-registers)
    if proto == "hdfs://" and "hdfs://" not in _fs_factories:
        import dmlc_tpu.io.webhdfs  # noqa: F401  (self-registers)
    if proto == "azure://" and "azure://" not in _fs_factories:
        import dmlc_tpu.io.azure  # noqa: F401  (self-registers)
    with _fs_lock:
        key = (proto, path.host)
        inst = _fs_instances.get(key)
        if inst is None:
            factory = _fs_factories.get(proto)
            if factory is None:
                raise DMLCError(
                    f"unknown filesystem protocol {proto!r} "
                    f"(known: {sorted(_fs_factories)})"
                )
            inst = factory(path)
            _fs_instances[key] = inst
    return inst


def _gated_backend(proto: str, hint: str):
    """The reference compile-gates hdfs/azure (DMLC_USE_HDFS/AZURE,
    src/io.cc:36-72) and errors at dispatch when absent; same contract."""

    def factory(uri: URI) -> FileSystem:
        raise DMLCError(
            f"{proto} support is not enabled in this build: {hint}"
        )

    return factory


register_filesystem("file://", lambda uri: LocalFileSystem())
register_filesystem("mem://", lambda uri: MemoryFileSystem())
register_filesystem("http://", lambda uri: HTTPFileSystem())
register_filesystem("https://", lambda uri: HTTPFileSystem())
# hdfs:// resolves lazily to the WebHDFS backend (io/webhdfs.py) on first
# use — see get_filesystem
register_filesystem(
    "viewfs://",
    _gated_backend("viewfs://", "resolve the mounttable to a concrete "
                   "hdfs:// namenode, or use an hdfs gateway mount"),
)
# azure:// resolves lazily to the Blob REST backend (io/azure.py) on
# first use — see get_filesystem


def create_stream(uri: str, flag: str, allow_null: bool = False) -> Optional[Stream]:
    """Stream::Create (src/io.cc:133-139)."""
    parsed = URI.parse(uri)
    fs = get_filesystem(parsed)
    if flag == "r":
        return fs.open_for_read(parsed, allow_null=allow_null)
    return fs.open(parsed, flag)


def create_stream_for_read(uri: str, allow_null: bool = False) -> Optional[SeekStream]:
    """SeekStream::CreateForRead (io.h:107)."""
    parsed = URI.parse(uri)
    return get_filesystem(parsed).open_for_read(parsed, allow_null=allow_null)


def _strip_end(s: str, ch: str) -> str:
    return s.rstrip(ch)


def expand_uri_patterns(uri: str, fs: Optional[FileSystem] = None) -> List[URI]:
    """Expand a ';'-separated list of URI patterns into concrete URIs.

    Mirrors InputSplitBase::ConvertToURIs (src/io/input_split_base.cc:96-147):
    each segment is matched against its parent directory's listing — an exact
    path match wins; otherwise the segment is treated as a regex that must
    full-match a listed file path (non-empty regular files only). Segments
    with no '/' (or ending in '/') pass through unexpanded.
    """
    out: List[URI] = []
    for part in uri.split(";"):
        if not part:
            continue
        parsed = URI.parse(part)
        part_fs = fs or get_filesystem(parsed)
        pos = parsed.name.rfind("/")
        if pos < 0 or pos + 1 == len(parsed.name):
            out.append(parsed)
            continue
        dir_uri = URI(parsed.protocol, parsed.host, parsed.name[:pos])
        try:
            dfiles = part_fs.list_directory(dir_uri)
        except (FileNotFoundError, OSError):
            out.append(parsed)
            continue
        target = _strip_end(parsed.name, "/")
        exact = [f for f in dfiles if _strip_end(f.path.name, "/") == target]
        if exact:
            out.append(exact[0].path)
            continue
        try:
            pattern = re.compile(parsed.name)
        except re.error as err:
            raise DMLCError(f"bad regex in uri {parsed.name!r}: {err}") from err
        matched = False
        for info in dfiles:
            if info.type != FILE_TYPE_FILE or info.size == 0:
                continue
            if pattern.fullmatch(_strip_end(info.path.name, "/")):
                out.append(info.path)
                matched = True
        if not matched:
            out.append(parsed)
    return out


def list_split_files(uri: str, recurse: bool = False) -> List[FileInfo]:
    """Resolve an InputSplit URI to the flat list of non-empty files.

    Mirrors InputSplitBase::InitInputFileInfo (input_split_base.cc:149-175):
    expand patterns, then expand directories (optionally recursively), keep
    only non-empty regular files; raise if nothing matched.
    """
    files: List[FileInfo] = []
    for parsed in expand_uri_patterns(uri):
        fs = get_filesystem(parsed)
        info = fs.get_path_info(parsed)
        if info.type == FILE_TYPE_DIR:
            sub = (
                fs.list_directory_recursive(parsed)
                if recurse
                else fs.list_directory(parsed)
            )
            files.extend(f for f in sub if f.type == FILE_TYPE_FILE and f.size > 0)
        elif info.size > 0:
            files.append(info)
    if not files:
        raise DMLCError(f"Cannot find any files that match the URI pattern {uri!r}")
    return files
