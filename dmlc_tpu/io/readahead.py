"""Parallel range-GET readahead for remote ingest.

The reference's remote-ingest engine is a hand-tuned *single* reconnecting
range-GET stream per InputSplit (src/io/s3_filesys.cc:219-445, reconnect
loop :319-342) feeding one prefetch thread.  On TPU hosts the network is
fast and the bottleneck is per-connection HTTP throughput, so this module
generalizes that design to N concurrent bounded range requests with
order-preserving delivery:

- the partition's global byte range is cut into fixed ``range_bytes``
  spans, intersected with file boundaries;
- a thread pool keeps ``connections`` requests in flight, each an
  independent ``FileSystem.read_range`` call (one bounded GET with its own
  per-range retry loop);
- results are yielded strictly in order behind a bounded window, so memory
  stays at ~``window × range_bytes`` and delivery is a sequential byte
  stream identical to what the single-connection reader would produce.

``RemotePartitionReader`` adds the reference's exactly-once partition
contract on top (input_split_base.cc:30-64): part k of n covers global
bytes [adj(k*step), adj((k+1)*step)) over the concatenated file sequence,
where adj(x) probes forward from x to just past the next end-of-line run
(line_split.cc:9-26).  The produced stream is pushed into the native
pipeline's push ABI (cpp/pipeline.cc ingest_push), which does the
record-boundary chunk cutting and threaded parse exactly as for local
files.
"""

from __future__ import annotations

import bisect
import concurrent.futures
import inspect
import threading
from collections import deque
from typing import Callable, Iterable, Iterator, List, Sequence, Tuple

from dmlc_tpu import obs
from dmlc_tpu.io.filesystem import URI, FileSystem
from dmlc_tpu.utils.logging import DMLCError, check

DEFAULT_RANGE_BYTES = 8 << 20   # reference chunk buffer: 8 MiB
# measured on a 1-core host: extra connections only add contention (507
# MB/s at 2 conns -> 380 at 8 on loopback), so the default scales with
# the cores available to run them; DMLC_TPU_READAHEAD_CONNS overrides
import os as _os

DEFAULT_CONNECTIONS = max(1, min(4, (_os.cpu_count() or 1)))


class PushRejected(Exception):
    """The native pipeline refused a push (it already failed or closed).

    Distinct from fetch errors so the feeder can let the pipeline's own
    error win instead of masking it with 'push failed' (the parse error
    the consumer is about to see is the real diagnosis)."""


class OrderedWindow:
    """Bounded ordered thread-pool map: the shared concurrency core of
    remote readahead (:func:`fetch_ordered`) and the pipelined chunk
    parser (data/pipeline.PipelinedParser).

    ``submit`` fans work onto ``workers`` threads; ``pop`` blocks on and
    returns the OLDEST submission's result, so delivery order is exactly
    submission order regardless of which worker finishes first. At most
    ``window`` (default 2×workers) items are in flight or buffered —
    the backpressure bound that keeps memory at ~window × item size. A
    failed call raises from ``pop`` at its in-order position; ``close``
    cancels everything still pending."""

    def __init__(
        self,
        fn: Callable,
        workers: int = DEFAULT_CONNECTIONS,
        window: int = 0,
        name: str = "readahead",
    ):
        from dmlc_tpu import obs  # deferred: io is a low layer

        self._fn = fn
        self.workers = max(1, workers)
        if window <= 0:
            window = 2 * self.workers
        self.window = max(window, self.workers)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix=name
        )
        self._pending: deque = deque()
        self._closed = False
        # process-wide stage counters (all windows share them: readahead
        # windows are transient, and totals are what skew reports want)
        reg = obs.registry()
        self._m_submitted = reg.counter(
            "dmlc_readahead_submitted_total",
            "items submitted to ordered windows")
        self._m_completed = reg.counter(
            "dmlc_readahead_completed_total",
            "items delivered in order from ordered windows")
        self._m_cancelled = reg.counter(
            "dmlc_readahead_cancelled_total",
            "pending items cancelled at window close")

    def __len__(self) -> int:
        return len(self._pending)

    @property
    def free_slots(self) -> int:
        return self.window - len(self._pending)

    def submit(self, item) -> None:
        check(not self._closed, "OrderedWindow is closed")
        self._m_submitted.inc()
        self._pending.append(self._pool.submit(self._fn, item))

    def pop(self):
        """Oldest submission's result (blocks). Errors re-raise here, in
        order, and poison the window: everything behind the failure is
        cancelled so a consumer that catches and retries cannot observe
        out-of-order survivors."""
        fut = self._pending.popleft()
        try:
            out = fut.result()
        except BaseException:
            self.close()
            raise
        self._m_completed.inc()
        return out

    def close(self) -> None:
        """Cancel pending work and release the pool (idempotent)."""
        if self._closed:
            return
        self._closed = True
        if self._pending:
            self._m_cancelled.inc(len(self._pending))
        for fut in self._pending:
            fut.cancel()
        self._pending.clear()
        self._pool.shutdown(wait=False, cancel_futures=True)


def fetch_ordered(
    fetch: Callable,
    items: Iterable,
    workers: int = DEFAULT_CONNECTIONS,
    window: int = 0,
) -> Iterator:
    """Map ``fetch`` over ``items`` with a thread pool, yielding results in
    submission order. At most ``window`` (default 2×workers) calls are in
    flight or buffered, bounding memory; a failed fetch propagates at its
    in-order position and cancels the rest."""
    win = OrderedWindow(fetch, workers=workers, window=window)
    try:
        for item in items:
            win.submit(item)
            if win.free_slots <= 0:
                yield win.pop()
        while len(win):
            yield win.pop()
    finally:
        win.close()


class RemotePartitionReader:
    """In-order byte stream of text partition k/n over remote files.

    ``files`` is the (path URI, size) list in dataset order; ``fs`` must
    implement ``read_range``. Iterating yields bytes buffers whose
    concatenation is exactly the partition's adjusted byte range — the
    stream the native pipeline's file reader would see, but fetched over
    ``connections`` parallel bounded range requests.
    """

    def __init__(
        self,
        fs: FileSystem,
        files: Sequence[Tuple[URI, int]],
        part: int,
        nparts: int,
        range_bytes: int = DEFAULT_RANGE_BYTES,
        connections: int = DEFAULT_CONNECTIONS,
        record_format: str = "text",
    ):
        check(0 <= part < nparts, "bad part %d/%d", part, nparts)
        check(record_format in ("text", "recordio"),
              "unknown record_format %r", record_format)
        self._record_format = record_format
        self._fs = fs
        self._cancel = threading.Event()
        # duck-typed filesystems may not take the cancelled kwarg
        try:
            self._supports_cancel = (
                "cancelled" in inspect.signature(fs.read_range).parameters
            )
        except (TypeError, ValueError):
            self._supports_cancel = False
        self._paths = [f[0] for f in files]
        self._sizes = [int(f[1]) for f in files]
        self._offsets = [0]
        for s in self._sizes:
            self._offsets.append(self._offsets[-1] + s)
        self._range_bytes = max(64 << 10, int(range_bytes))
        self._connections = max(1, int(connections))
        total = self._offsets[-1]
        nstep = (total + nparts - 1) // nparts
        # recordio steps stay 4B-aligned, matching input_split.py
        # reset_partition and pipeline.cc ReaderMain (same-part guarantee
        # for boundary records across all three stacks)
        align = 4 if record_format == "recordio" else 1
        nstep = (nstep + align - 1) // align * align
        raw_begin = min(nstep * part, total)
        raw_end = min(nstep * (part + 1), total)
        if raw_begin >= raw_end:
            self.begin = self.end = total
        else:
            self.begin = self._adjust_boundary(raw_begin)
            self.end = self._adjust_boundary(raw_end)

    # ---- partition boundary adjustment -------------------------------

    def _global_read(self, pos: int, n: int) -> bytes:
        """Read up to n bytes at global offset pos, spanning files."""
        out = bytearray()
        total = self._offsets[-1]
        while n > 0 and pos < total:
            idx = bisect.bisect_right(self._offsets, pos) - 1
            local = pos - self._offsets[idx]
            want = min(n, self._sizes[idx] - local)
            got = self._fs.read_range(self._paths[idx], local, want)
            if not got:
                break
            out.extend(got)
            pos += len(got)
            n -= len(got)
        return bytes(out)

    def _adjust_boundary(self, pos: int) -> int:
        """adj(x): first record begin at global offset >= x (0 stays 0).
        Text probes forward past the next end-of-line run
        (line_split.cc:9-26); recordio scans aligned words for a head frame
        (recordio_split.cc:9-25 — exact, since packing elides aligned
        embedded magics; see cpp/pipeline.cc AdjustBoundaryRecordIO)."""
        if pos <= 0:
            return 0
        total = self._offsets[-1]
        if pos >= total:
            return total
        if self._record_format == "recordio":
            return self._adjust_boundary_recordio(pos, total)
        seen_eol = False
        while pos < total:
            probe = self._global_read(pos, 4096)
            if not probe:
                return total
            for i, c in enumerate(probe):
                if c in (0x0A, 0x0D):
                    seen_eol = True
                elif seen_eol:
                    return pos + i
            pos += len(probe)
        return total

    def _adjust_boundary_recordio(self, pos: int, total: int) -> int:
        import numpy as np

        from dmlc_tpu.io import recordio as _rio

        base = (pos + 3) & ~3  # heads sit on 4B alignment
        carry = b""
        while base + len(carry) < total:
            probe = self._global_read(base + len(carry), 1 << 16)
            if not probe:
                break
            buf = carry + probe
            words = np.frombuffer(
                buf[: len(buf) & ~3], dtype="<u4"
            )
            if len(words) >= 2:
                hits = np.nonzero(words[:-1] == _rio.RECORDIO_MAGIC)[0]
                flags = (words[hits + 1] >> 29) & 7
                good = hits[(flags == 0) | (flags == 1)]
                if good.size:
                    return base + (int(good[0]) << 2)
            # keep the unscanned aligned tail (< 8 bytes)
            processed = max(0, (len(buf) - 4) & ~3)
            carry = buf[processed:]
            base += processed
        return total

    # ---- ranged fetch plan -------------------------------------------

    def ranges(self) -> List[Tuple[int, int, int]]:
        """[(file_idx, local_offset, length)] covering [begin, end) in
        fixed spans intersected with file boundaries."""
        out: List[Tuple[int, int, int]] = []
        pos = self.begin
        while pos < self.end:
            idx = bisect.bisect_right(self._offsets, pos) - 1
            local = pos - self._offsets[idx]
            length = min(
                self._range_bytes,
                self.end - pos,
                self._sizes[idx] - local,
            )
            out.append((idx, local, length))
            pos += length
        return out

    @property
    def nbytes(self) -> int:
        return self.end - self.begin

    def cancel(self) -> None:
        """Stop in-flight fetch retries promptly (teardown path): pending
        fetchers fail at their next retry/cancellation checkpoint instead
        of running out their full retry budgets."""
        self._cancel.set()

    # ---- direct native feed ------------------------------------------

    @property
    def prefers_direct_feed(self) -> bool:
        """With one connection there is no fetch parallelism to preserve,
        so the feeder should stream each range straight into the native
        push buffer (readinto → zero Python-side copies)."""
        return self._connections == 1

    def supports_into(self) -> bool:
        try:
            return (
                "into" in inspect.signature(self._fs.read_range).parameters
            )
        except (TypeError, ValueError):
            return False

    def feed_into(self, pipe) -> None:
        """Sequential fetch of every range directly into ``pipe``'s push
        buffer (ingest_push_reserve/commit): remote body bytes are written
        once, into native memory, instead of bytearray→memcpy. Raises
        PushRejected when the pipeline itself failed (its error wins);
        fetch errors raise normally so the feeder aborts the pipeline."""
        use_into = self.supports_into()
        for idx, local, length in self.ranges():
            if self._cancel.is_set():
                raise DMLCError("readahead cancelled")
            try:
                view = pipe.push_reserve(length)
            except DMLCError as err:
                raise PushRejected(str(err)) from err
            if use_into:
                got = self._fs.read_range(
                    self._paths[idx], local, length,
                    cancelled=(self._cancel.is_set
                               if self._supports_cancel else None),
                    into=view,
                )
            elif self._supports_cancel:
                data = self._fs.read_range(
                    self._paths[idx], local, length,
                    cancelled=self._cancel.is_set,
                )
                got = len(data)
                view[:got] = data
            else:
                data = self._fs.read_range(self._paths[idx], local, length)
                got = len(data)
                view[:got] = data
            check(
                got == length,
                "short range read on %s at %d: got %d of %d bytes "
                "(file changed during ingest?)",
                self._paths[idx].str_full(), local, got, length,
            )
            try:
                pipe.push_commit(length)
            except DMLCError as err:
                raise PushRejected(str(err)) from err

    def __iter__(self) -> Iterator[bytes]:
        from dmlc_tpu.params.knobs import hedge_threshold_s
        from dmlc_tpu.resilience import faultpoint, hedged_call

        hedge_s = hedge_threshold_s()

        def fetch_once(rng: Tuple[int, int, int]) -> bytes:
            idx, local, length = rng
            if self._cancel.is_set():
                raise DMLCError("readahead cancelled")
            faultpoint("readahead.fetch")
            if self._supports_cancel:
                data = self._fs.read_range(
                    self._paths[idx], local, length,
                    cancelled=self._cancel.is_set,
                )
            else:
                data = self._fs.read_range(self._paths[idx], local, length)
            check(
                len(data) == length,
                "short range read on %s at %d: got %d of %d bytes "
                "(file changed during ingest?)",
                self._paths[idx].str_full(), local, len(data), length,
            )
            return data

        def fetch(rng: Tuple[int, int, int]):
            # hedging is only safe here: fetch_once allocates its own
            # buffer per attempt, so a duplicated request cannot race a
            # shared destination (the feed_into/into= path must never
            # hedge — two winners into one buffer is corruption)
            fid = obs.new_flow()
            with obs.span("readahead_fetch", nbytes=rng[2], flow=fid):
                data = hedged_call(lambda: fetch_once(rng), hedge_s)
                obs.flow_start(fid, "range")
            return fid, data

        def deliver() -> Iterator[bytes]:
            # range-level flow arrows: fetch-worker slice → the consumer
            # thread's pop. Chunk-level flows (PipelinedParser) start one
            # layer up; these show which connection served which range.
            for fid, data in fetch_ordered(
                fetch, self.ranges(), workers=self._connections
            ):
                with obs.span(
                    "readahead_deliver", nbytes=len(data), flow=fid
                ):
                    obs.flow_end(fid, "range")
                yield data

        return deliver()
