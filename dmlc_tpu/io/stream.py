"""Stream / SeekStream abstractions and in-memory implementations.

Capability parity: ``dmlc::Stream`` Read/Write (reference io.h:29-86),
``SeekStream`` (io.h:89-107), ``Serializable`` (io.h:112-126), and the
in-memory streams of memory_io.h (MemoryFixedSizeStream:21,
MemoryStringStream:66).
"""

from __future__ import annotations

import io as _pyio
import struct
from typing import Optional, Protocol, runtime_checkable


class Stream:
    """Abstract byte stream.

    ``read(n)`` returns up to ``n`` bytes (b"" at EOF); ``write(data)`` writes
    all bytes. Typed helpers mirror the reference's templated Write/Read
    (io.h:68-86): little-endian fixed-width scalars and length-prefixed blobs.
    """

    def read(self, nbytes: int) -> bytes:
        raise NotImplementedError

    def write(self, data: bytes) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self) -> "Stream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- exact-size reads ---------------------------------------------
    def read_exact(self, nbytes: int) -> bytes:
        """Read exactly nbytes or raise EOFError (partial read at EOF raises)."""
        chunks = []
        remaining = nbytes
        while remaining > 0:
            chunk = self.read(remaining)
            if not chunk:
                raise EOFError(
                    f"Stream ended: wanted {nbytes} bytes, got {nbytes - remaining}"
                )
            chunks.append(chunk)
            remaining -= len(chunk)
        return b"".join(chunks)

    def try_read_exact(self, nbytes: int) -> Optional[bytes]:
        """Like read_exact but returns None on clean EOF at a record boundary."""
        first = self.read(nbytes)
        if not first:
            return None
        if len(first) == nbytes:
            return first
        rest = self.read_exact(nbytes - len(first))
        return first + rest

    # ---- typed scalar helpers (little-endian, like the reference on all
    # supported platforms — endian.h) -----------------------------------
    def write_fmt(self, fmt: str, *values) -> None:
        self.write(struct.pack("<" + fmt, *values))

    def read_fmt(self, fmt: str):
        size = struct.calcsize("<" + fmt)
        vals = struct.unpack("<" + fmt, self.read_exact(size))
        return vals if len(vals) > 1 else vals[0]

    def write_uint32(self, v: int) -> None:
        self.write_fmt("I", v)

    def read_uint32(self) -> int:
        return self.read_fmt("I")

    def write_uint64(self, v: int) -> None:
        self.write_fmt("Q", v)

    def read_uint64(self) -> int:
        return self.read_fmt("Q")

    def write_bytes_prefixed(self, data: bytes) -> None:
        """Length(u64)-prefixed blob — matches Stream::Write(std::string)
        shape (serializer.h string handler)."""
        self.write_uint64(len(data))
        self.write(data)

    def read_bytes_prefixed(self) -> bytes:
        return self.read_exact(self.read_uint64())


class SeekStream(Stream):
    """Stream with random access (reference io.h:89-107)."""

    def seek(self, pos: int) -> None:
        raise NotImplementedError

    def tell(self) -> int:
        raise NotImplementedError


@runtime_checkable
class Serializable(Protocol):
    """Objects that can round-trip through a Stream (reference io.h:112-126)."""

    def save(self, stream: Stream) -> None: ...

    def load(self, stream: Stream) -> None: ...


class FileObjStream(SeekStream):
    """Adapter from any Python binary file object (reference dmlc::istream/
    ostream adapters play the inverse role, io.h:298-422)."""

    def __init__(self, fileobj, seekable: bool = True):
        self._f = fileobj
        self._seekable = seekable

    def read(self, nbytes: int) -> bytes:
        return self._f.read(nbytes)

    def write(self, data: bytes) -> None:
        self._f.write(data)

    def seek(self, pos: int) -> None:
        self._f.seek(pos)

    def tell(self) -> int:
        return self._f.tell()

    def close(self) -> None:
        self._f.close()


class MemoryStream(SeekStream):
    """Growable in-memory stream (reference MemoryStringStream,
    memory_io.h:66-102)."""

    def __init__(self, data: bytes = b""):
        self._buf = _pyio.BytesIO(data)

    def read(self, nbytes: int) -> bytes:
        return self._buf.read(nbytes)

    def write(self, data: bytes) -> None:
        self._buf.write(data)

    def seek(self, pos: int) -> None:
        self._buf.seek(pos)

    def tell(self) -> int:
        return self._buf.tell()

    def getvalue(self) -> bytes:
        return self._buf.getvalue()


class FixedMemoryStream(SeekStream):
    """SeekStream over a fixed-size caller-owned buffer (reference
    MemoryFixedSizeStream, memory_io.h:21-63): writes past the end raise."""

    def __init__(self, buf: bytearray | memoryview):
        self._view = memoryview(buf)
        self._pos = 0

    def read(self, nbytes: int) -> bytes:
        end = min(self._pos + nbytes, len(self._view))
        out = bytes(self._view[self._pos : end])
        self._pos = end
        return out

    def write(self, data: bytes) -> None:
        end = self._pos + len(data)
        if end > len(self._view):
            raise IOError(
                f"FixedMemoryStream overflow: {end} > {len(self._view)}"
            )
        self._view[self._pos : end] = data
        self._pos = end

    def seek(self, pos: int) -> None:
        if pos < 0 or pos > len(self._view):
            raise IOError(f"seek out of range: {pos}")
        self._pos = pos

    def tell(self) -> int:
        return self._pos
