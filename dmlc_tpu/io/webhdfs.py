"""HDFS filesystem over the WebHDFS REST API (``hdfs://``).

The reference's HDFS backend wraps libhdfs JNI (src/io/hdfs_filesys.{h,cc}:
refcounted hdfsFS connections, EINTR-safe reads, compile-gated behind
DMLC_USE_HDFS). A JVM dependency is the wrong shape for TPU host images, so
this build speaks WebHDFS — the REST API every namenode serves — with
nothing beyond the stdlib:

- ``hdfs://host:port/path`` → ``http://host:port/webhdfs/v1/path``; the URI
  host should name the namenode's **HTTP** address (default port 9870), or
  set ``DMLC_WEBHDFS_ENDPOINT`` to the REST base to keep RPC-style URIs.
- reads: ``op=OPEN&offset=N`` through the shared RangedReadStream, so HDFS
  reads get the same lazy-seek + reconnect-retry behavior as s3/gs
  (the reference's EINTR retry, hdfs_filesys.cc:31-49, generalized).
- writes: ``op=CREATE`` then ``op=APPEND`` per buffered part (64 MB default
  like the object stores), following WebHDFS's two-step redirect dance
  (namenode 307 → datanode PUT/POST).
- listing/stat: ``op=LISTSTATUS`` / ``op=GETFILESTATUS``.
- auth: pseudo-auth ``user.name`` from ``HADOOP_USER_NAME`` (kerberos is
  out of scope — front the cluster with a gateway, e.g. Knox, and point
  DMLC_WEBHDFS_ENDPOINT at it).

Tests run against an in-process fake namenode/datanode
(tests/fake_webhdfs.py) — hermetic coverage the reference never had for
HDFS (SURVEY §4: manual live-cluster scripts only).
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import List, Optional

from dmlc_tpu.io.filesystem import (
    FILE_TYPE_DIR,
    FILE_TYPE_FILE,
    FileInfo,
    FileSystem,
    RangedReadStream,
    URI,
    read_range_with_retry,
    register_filesystem,
)
from dmlc_tpu.io.object_store import ObjectWriteStream
from dmlc_tpu.io.stream import SeekStream, Stream
from dmlc_tpu.utils.logging import check

READ_MAX_RETRY = 50
READ_RETRY_SLEEP_S = 0.1
WRITE_MAX_RETRY = 3  # idempotent CREATE only; APPEND is single-shot
DEFAULT_WRITE_BUFFER_MB = 64
DEFAULT_HTTP_PORT = 9870  # namenode web UI / WebHDFS default


class _NoRedirect(urllib.request.HTTPRedirectHandler):
    """Surface 307s instead of following them: WebHDFS redirects PUT/POST
    bodies to a datanode, and the client must re-send the body there."""

    def redirect_request(self, req, fp, code, msg, headers, newurl):
        return None


_no_redirect_opener = urllib.request.build_opener(_NoRedirect)


class WebHDFSFileSystem(FileSystem):
    """FileSystem speaking WebHDFS (see module docstring)."""

    def __init__(self, uri: URI):
        endpoint = os.environ.get("DMLC_WEBHDFS_ENDPOINT", "")
        if endpoint:
            self._base = endpoint.rstrip("/")
        else:
            check(uri.host, "hdfs:// URI needs a namenode host")
            host = uri.host
            if ":" not in host:
                host = f"{host}:{DEFAULT_HTTP_PORT}"
            self._base = f"http://{host}/webhdfs/v1"
        self._user = os.environ.get("HADOOP_USER_NAME", "")
        self._part_bytes = (
            int(os.environ.get("DMLC_HDFS_WRITE_BUFFER_MB",
                               DEFAULT_WRITE_BUFFER_MB)) << 20
        )

    # ---- REST plumbing -------------------------------------------------

    def _url(self, path: str, op: str, **params) -> str:
        query = {"op": op, **params}
        if self._user:
            query["user.name"] = self._user
        return (
            self._base
            + urllib.parse.quote(path)
            + "?"
            + urllib.parse.urlencode(query)
        )

    def _json(self, method: str, path: str, op: str, **params) -> dict:
        req = urllib.request.Request(
            self._url(path, op, **params), method=method
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            body = resp.read()
        return json.loads(body) if body else {}

    def _two_step_write(self, method: str, path: str, op: str,
                        data: bytes, **params) -> None:
        """The CREATE/APPEND dance: ask the namenode (no body), get the 307
        datanode location, re-send there with the payload."""
        url = self._url(path, op, **params)
        req = urllib.request.Request(url, method=method)
        location = None
        try:
            with _no_redirect_opener.open(req, timeout=60) as resp:
                # some gateways answer 200/201 directly with no redirect
                location = resp.headers.get("Location")
        except urllib.error.HTTPError as err:
            if err.code in (301, 302, 307):
                location = err.headers.get("Location")
                err.close()
            else:
                raise
        target = location or url
        req2 = urllib.request.Request(
            target, data=data, method=method,
            headers={"Content-Type": "application/octet-stream"},
        )
        with urllib.request.urlopen(req2, timeout=300):
            pass

    @staticmethod
    def _display(path: URI) -> str:
        return path.str_full()

    # ---- FileSystem interface ------------------------------------------

    def _status(self, path: URI) -> Optional[dict]:
        try:
            out = self._json("GET", path.name or "/", "GETFILESTATUS")
        except urllib.error.HTTPError as err:
            if err.code == 404:
                err.close()
                return None
            raise
        return out.get("FileStatus")

    def get_path_info(self, path: URI) -> FileInfo:
        status = self._status(path)
        if status is None:
            raise FileNotFoundError(self._display(path))
        is_dir = status.get("type") == "DIRECTORY"
        return FileInfo(
            path=path,
            size=0 if is_dir else int(status.get("length", 0)),
            type=FILE_TYPE_DIR if is_dir else FILE_TYPE_FILE,
        )

    def list_directory(self, path: URI) -> List[FileInfo]:
        out = self._json("GET", path.name or "/", "LISTSTATUS")
        entries = out.get("FileStatuses", {}).get("FileStatus", [])
        base = (path.name or "/").rstrip("/")
        infos: List[FileInfo] = []
        for st in entries:
            suffix = st.get("pathSuffix", "")
            sub_name = f"{base}/{suffix}" if suffix else (base or "/")
            sub = URI(path.protocol, path.host, sub_name)
            is_dir = st.get("type") == "DIRECTORY"
            infos.append(
                FileInfo(
                    path=sub,
                    size=0 if is_dir else int(st.get("length", 0)),
                    type=FILE_TYPE_DIR if is_dir else FILE_TYPE_FILE,
                )
            )
        infos.sort(key=lambda fi: fi.path.name)
        return infos

    def open_for_read(
        self, path: URI, allow_null: bool = False
    ) -> Optional[SeekStream]:
        status = self._status(path)
        if status is None or status.get("type") == "DIRECTORY":
            if allow_null:
                return None
            raise FileNotFoundError(self._display(path))
        size = int(status.get("length", 0))

        def open_ranged(start: int):
            # namenode 307s OPEN to a datanode; urllib follows GETs itself
            return urllib.request.urlopen(
                self._url(path.name, "OPEN", offset=start), timeout=60
            )

        return RangedReadStream(
            open_ranged, size, self._display(path),
            max_retry=READ_MAX_RETRY, retry_sleep_s=READ_RETRY_SLEEP_S,
        )

    def read_range(
        self, path: URI, offset: int, length: int, cancelled=None
    ) -> bytes:
        """One bounded OPEN per call (WebHDFS supports offset+length
        natively) — the parallel-readahead primitive, with per-range retry
        like the object stores (shared loop: read_range_with_retry)."""

        def open_ranged(start: int, end: int):
            return urllib.request.urlopen(
                self._url(path.name, "OPEN", offset=start, length=end - start),
                timeout=60,
            )

        return read_range_with_retry(
            open_ranged, offset, length, self._display(path),
            max_retry=READ_MAX_RETRY, retry_sleep_s=READ_RETRY_SLEEP_S,
            cancelled=cancelled,
        )

    def open(self, path: URI, flag: str) -> Stream:
        check(flag in ("r", "w"), "hdfs supports flags r/w, not %s", flag)
        if flag == "r":
            stream = self.open_for_read(path)
            assert stream is not None
            return stream
        return _WebHDFSWriteStream(self, path)


class _WebHDFSWriteStream(ObjectWriteStream):
    """Buffered CREATE-then-APPEND writer: the object stores' part-upload
    base with HDFS's two REST steps. The retry split follows idempotency:
    CREATE with ``overwrite=true`` replaces the whole file, so a resend
    after an ambiguous failure converges on the same bytes and retries
    under the shared policy; APPEND is NOT idempotent — if the datanode
    committed the bytes but the ack was lost, a blind resend duplicates
    them — so APPEND stays single-shot and pipeline recovery is HDFS's
    job. The base's close() marks the stream closed BEFORE the final
    flush, so a failed close is not re-flushed from __del__."""

    def __init__(self, fs: WebHDFSFileSystem, path: URI):
        super().__init__(fs._part_bytes)
        self._fs = fs
        self._path = path
        self._created = False

    def _upload_part(self, data: bytes, last: bool) -> None:
        from dmlc_tpu.resilience import RetryPolicy

        if not self._created:
            RetryPolicy(
                max_attempts=WRITE_MAX_RETRY, base_s=READ_RETRY_SLEEP_S
            ).call(
                lambda: self._fs._two_step_write(
                    "PUT", self._path.name, "CREATE", data, overwrite="true"
                ),
                "io.hdfs.create",
                display=f"webhdfs CREATE {self._path.name}",
            )
            self._created = True
        elif data:
            # single-shot on purpose: see the class docstring
            self._fs._two_step_write(
                "POST", self._path.name, "APPEND", data
            )

    def _finalize(self) -> None:
        pass  # every byte is durable once its CREATE/APPEND returned


def _factory(uri: URI) -> FileSystem:
    return WebHDFSFileSystem(uri)


register_filesystem("hdfs://", _factory)
