"""Azure Blob Storage backend: ``azure://container/blob``.

The reference ships listing only, through the Azure C++ SDK
(/root/reference/src/io/azure_filesys.cc:32-92, account/key from
AZURE_STORAGE_ACCOUNT / AZURE_STORAGE_ACCESS_KEY). This build implements
the Blob REST dialect directly — list, stat, ranged reads (the parallel
readahead primitive) AND block-committed writes — through the same
``_ObjectStoreBase`` machinery as S3/GCS, so every ingest path (local
InputSplit stack, native push-mode readahead) works over azure:// too.

Auth, either of:
- shared key: AZURE_STORAGE_ACCOUNT + AZURE_STORAGE_ACCESS_KEY (base64),
  signing requests per the SharedKey scheme;
- SAS: AZURE_STORAGE_ACCOUNT + AZURE_STORAGE_SAS_TOKEN appended to each
  request's query string;
- neither set: anonymous (public containers, or a fake test endpoint).

AZURE_STORAGE_ENDPOINT overrides ``https://{account}.blob.core.windows.net``
(hermetic tests point it at tests/fake_azure.py).
"""

from __future__ import annotations

import base64  # noqa: I001
import hashlib
import hmac
import os
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from email.utils import formatdate
from typing import Dict, List, Optional, Tuple

from dmlc_tpu.io.filesystem import URI
from dmlc_tpu.io.object_store import (
    DEFAULT_WRITE_BUFFER_MB,
    ObjectWriteStream,
    _http,
    _keepalive_get,
    _ObjectStoreBase,
    _write_call,
)
from dmlc_tpu.io.stream import Stream
from dmlc_tpu.utils.logging import check

_API_VERSION = "2021-08-06"

# headers that participate in the SharedKey string-to-sign, in order
_SIGNED_STD_HEADERS = (
    "Content-Encoding", "Content-Language", "Content-Length", "Content-MD5",
    "Content-Type", "Date", "If-Modified-Since", "If-Match", "If-None-Match",
    "If-Unmodified-Since", "Range",
)


def _rfc1123_now() -> str:
    return formatdate(timeval=None, localtime=False, usegmt=True)


class AzureBlobFileSystem(_ObjectStoreBase):
    """``azure://container/blob`` via Blob service REST."""

    def __init__(self):
        env = os.environ
        self.account = env.get("AZURE_STORAGE_ACCOUNT", "")
        self.key = env.get(
            "AZURE_STORAGE_ACCESS_KEY", env.get("AZURE_STORAGE_KEY", "")
        )
        self.sas = env.get("AZURE_STORAGE_SAS_TOKEN", "").lstrip("?")
        endpoint = env.get("AZURE_STORAGE_ENDPOINT")
        if not endpoint:
            check(
                bool(self.account),
                "set AZURE_STORAGE_ACCOUNT (and ACCESS_KEY or SAS_TOKEN) "
                "to use azure:// (azure_filesys.cc:32-39 contract)",
            )
            endpoint = f"https://{self.account}.blob.core.windows.net"
        self.endpoint = endpoint.rstrip("/")
        self.part_bytes = (
            int(env.get("DMLC_AZURE_WRITE_BUFFER_MB",
                        env.get("DMLC_S3_WRITE_BUFFER_MB",
                                DEFAULT_WRITE_BUFFER_MB)))
            << 20
        )

    # ---- request plumbing ---------------------------------------------

    def _url(self, container: str, key: str, query: str = "") -> str:
        path = f"/{container}"
        if key:
            path += f"/{urllib.parse.quote(key)}"
        if self.sas:
            query = f"{query}&{self.sas}" if query else self.sas
        return self.endpoint + path + (f"?{query}" if query else "")

    def _auth_headers(
        self, method: str, url: str, headers: Dict[str, str],
        content_length: int = 0,
    ) -> Dict[str, str]:
        """x-ms-date/version plus the SharedKey Authorization header
        (skipped under SAS/anonymous auth)."""
        out = dict(headers)
        out.setdefault("x-ms-date", _rfc1123_now())
        out.setdefault("x-ms-version", _API_VERSION)
        if not (self.account and self.key) or self.sas:
            return out
        parsed = urllib.parse.urlsplit(url)
        canon_headers = "".join(
            f"{k.lower()}:{v.strip()}\n"
            for k, v in sorted(out.items())
            if k.lower().startswith("x-ms-")
        )
        # canonicalized resource: /account/path + sorted query params
        resource = f"/{self.account}{parsed.path}"
        params = urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)
        for name, value in sorted(params):
            resource += f"\n{name.lower()}:{value}"
        values = dict.fromkeys(_SIGNED_STD_HEADERS, "")
        values["Content-Length"] = (
            str(content_length) if content_length else ""
        )
        for k, v in out.items():
            title = k.title()
            if title in values and not title.startswith("X-Ms-"):
                values[title] = v
        to_sign = (
            method + "\n"
            + "\n".join(values[h] for h in _SIGNED_STD_HEADERS) + "\n"
            + canon_headers + resource
        )
        sig = base64.b64encode(
            hmac.new(
                base64.b64decode(self.key), to_sign.encode("utf-8"),
                hashlib.sha256,
            ).digest()
        ).decode()
        out["Authorization"] = f"SharedKey {self.account}:{sig}"
        return out

    def _request(self, method: str, url: str, payload: bytes = b"",
                 headers: Optional[Dict[str, str]] = None):
        hdrs = dict(headers or {})
        if payload:
            # urllib injects Content-Type on bodied requests; it must be
            # explicit so the SharedKey string-to-sign matches the wire
            hdrs.setdefault("Content-Type", "application/octet-stream")
        hdrs = self._auth_headers(
            method, url, hdrs, content_length=len(payload)
        )
        req = urllib.request.Request(
            url, data=payload if payload else None, headers=hdrs,
            method=method,
        )
        return _http(req)

    # ---- reads ---------------------------------------------------------

    def _open_ranged(self, path: URI, start: int, end: Optional[int] = None):
        container, key = self._bucket_key(path)
        url = self._url(container, key)
        hdrs = self._auth_headers(
            "GET", url, {"Range": self._range_header(start, end)}
        )
        if end is not None:  # bounded: body fully drained, safe to reuse
            return _keepalive_get(url, hdrs)
        return _http(urllib.request.Request(url, headers=hdrs))

    def _stat_object(self, path: URI) -> Optional[int]:
        container, key = self._bucket_key(path)
        if not key:
            return None
        url = self._url(container, key)
        try:
            with self._request("HEAD", url) as resp:
                return int(resp.headers.get("Content-Length", 0))
        except urllib.error.HTTPError as err:
            if err.code in (404, 403):
                return None
            raise

    def _list(self, container: str, prefix: str, delimiter: str):
        """List Blobs (flat, hierarchical with delimiter): the capability
        the reference's ListDirectory provides (azure_filesys.cc:42-92)."""
        files: List[Tuple[str, int]] = []
        prefixes: List[str] = []
        marker = None
        while True:
            q = [("restype", "container"), ("comp", "list")]
            if prefix:
                q.append(("prefix", prefix))
            if delimiter:
                q.append(("delimiter", delimiter))
            if marker:
                q.append(("marker", marker))
            url = self._url(container, "", urllib.parse.urlencode(q))
            with self._request("GET", url) as resp:
                tree = ET.fromstring(resp.read())
            blobs = tree.find("Blobs")
            if blobs is not None:
                for item in blobs.findall("Blob"):
                    name = item.findtext("Name")
                    size = int(
                        item.findtext("Properties/Content-Length", "0")
                    )
                    files.append((name, size))
                for item in blobs.findall("BlobPrefix"):
                    prefixes.append(item.findtext("Name"))
            marker = tree.findtext("NextMarker")
            if not marker:
                break
        return files, prefixes

    # ---- writes: Put Block + Put Block List ---------------------------

    class _AzureWriteStream(ObjectWriteStream):
        def __init__(self, fs: "AzureBlobFileSystem", path: URI):
            super().__init__(fs.part_bytes)
            self._fs = fs
            self._path = path
            self._block_ids: List[str] = []

        def _upload_part(self, data: bytes, last: bool) -> None:
            fs, (container, key) = self._fs, self._fs._bucket_key(self._path)
            if last and not self._block_ids:
                # single-shot Put Blob (the common small-object case)
                url = fs._url(container, key)

                def _put():
                    with fs._request(
                        "PUT", url, payload=data,
                        headers={"x-ms-blob-type": "BlockBlob"},
                    ):
                        pass

                _write_call(_put, "io.azure.write", f"azure Put Blob {key}")
                self._block_ids = None  # finalize becomes a no-op
                return
            if not data and last:
                return
            block_id = base64.b64encode(
                f"{len(self._block_ids):010d}".encode()
            ).decode()

            def _put_block():
                url = fs._url(
                    container, key,
                    urllib.parse.urlencode(
                        [("comp", "block"), ("blockid", block_id)]
                    ),
                )
                with fs._request("PUT", url, payload=data):
                    pass

            _write_call(_put_block, "io.azure.write", f"azure Put Block {key}")
            self._block_ids.append(block_id)

        def _finalize(self) -> None:
            if self._block_ids is None:
                return  # single-shot Put Blob path
            fs, (container, key) = self._fs, self._fs._bucket_key(self._path)
            body = (
                "<?xml version=\"1.0\" encoding=\"utf-8\"?><BlockList>"
                + "".join(
                    f"<Latest>{b}</Latest>" for b in self._block_ids
                )
                + "</BlockList>"
            ).encode()

            def _commit():
                url = fs._url(
                    container, key, urllib.parse.urlencode([("comp",
                                                             "blocklist")])
                )
                with fs._request("PUT", url, payload=body):
                    pass

            _write_call(_commit, "io.azure.write", f"azure Put Block List {key}")

    def _open_write(self, path: URI) -> Stream:
        return self._AzureWriteStream(self, path)

    def delete(self, path: URI) -> None:
        container, key = self._bucket_key(path)

        def _delete():
            with self._request("DELETE", self._url(container, key)):
                pass

        # Delete Blob is idempotent; retry like the other backends do
        _write_call(_delete, "io.azure.delete", f"azure Delete Blob {key}")


from dmlc_tpu.io.filesystem import register_filesystem  # noqa: E402

register_filesystem("azure://", lambda uri: AzureBlobFileSystem())
