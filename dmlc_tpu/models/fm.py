"""Factorization machines over COO device batches.

The libfm format the reference parses (libfm_parser.h) exists to feed this
model family; the reference ships the parser and leaves the model downstream.
TPU-first formulation: all per-entry work is gathers + segment_sums (static
shapes), and the O(nnz·K) factor math is batched so XLA can keep it on the
vector units; the factor table gradient is one scatter-add.

score(x) = b + Σ_i w_i x_i + ½ Σ_k [(Σ_i v_ik x_i)² − Σ_i v_ik² x_i²]
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from dmlc_tpu.utils.jax_compat import shard_map

from dmlc_tpu.collective.device import bucketed_psum
from dmlc_tpu.models.linear import (
    _margin_grad,
    _suppress_donation_warnings,
    step_batch,
)
from dmlc_tpu.obs.device_telemetry import instrumented_jit
from dmlc_tpu.ops.spmv import expand_row_ids, spmv, spmv_transpose
from dmlc_tpu.parallel.partition import match_partition_rules, shard_params
from dmlc_tpu.params.parameter import Parameter, field
from dmlc_tpu.utils.logging import check


class FMParam(Parameter):
    objective = field(str, "logistic")
    learning_rate = field(float, 0.05, lower_bound=0.0)
    l2 = field(float, 0.0, lower_bound=0.0)
    num_factors = field(int, 8, lower_bound=1)
    num_features = field(int, 0)
    init_scale = field(float, 0.01, lower_bound=0.0)


def init_fm_params(
    num_features: int, num_factors: int, init_scale: float = 0.01, seed: int = 0
) -> Dict:
    key = jax.random.PRNGKey(seed)
    return {
        "w": jnp.zeros((num_features,), dtype=jnp.float32),
        "b": jnp.zeros((), dtype=jnp.float32),
        "v": init_scale
        * jax.random.normal(key, (num_features, num_factors), dtype=jnp.float32),
    }


#: Data-parallel placement for {"w": [F], "b": scalar, "v": [F, K]}:
#: everything replicated, the batch shards, grads psum in-graph. Linted
#: by scripts/check_partition_rules.py like LINEAR_PARTITION_RULES.
FM_PARTITION_RULES = ((r"^(w|b|v)$", P()),)


def _fm_forward_grads(params, batch, objective: str, num_features: int):
    """Local (unreduced) grads + loss sums for one COO batch shard."""
    label = batch["label"]
    weight = batch["weight"]
    values = batch["values"]
    indices = batch["indices"]
    # offsets → row ids on device (local per shard under shard_map)
    row_ids = expand_row_ids(batch["offsets"], values.shape[0])
    num_rows = label.shape[0]

    v_e = jnp.take(params["v"], indices, axis=0)  # [nnz, K]
    xv = values[:, None] * v_e  # [nnz, K]
    s = jax.ops.segment_sum(xv, row_ids, num_segments=num_rows)  # [B, K]
    q = jax.ops.segment_sum(xv * xv, row_ids, num_segments=num_rows)
    linear = spmv(values, indices, row_ids, params["w"], num_rows)
    margin = params["b"] + linear + 0.5 * jnp.sum(s * s - q, axis=-1)

    loss, gmargin = _margin_grad(objective, margin, label)
    wg = weight * gmargin  # [B]

    gw = spmv_transpose(values, indices, row_ids, wg, num_features)
    gb = jnp.sum(wg)
    # dv[i,k]: per entry x_e * (s[r,k] − x_e v[i,k]), scaled by wg[r]
    s_e = jnp.take(s, row_ids, axis=0)  # [nnz, K]
    dv_entry = (wg[row_ids] * values)[:, None] * (s_e - xv)
    gv = jax.ops.segment_sum(dv_entry, indices, num_segments=num_features)
    return gw, gb, gv, jnp.sum(weight * loss), jnp.sum(weight)


def make_fm_train_step(
    mesh: Optional[Mesh],
    num_features: int,
    objective: str = "logistic",
    learning_rate: float = 0.05,
    l2: float = 0.0,
    axis: str = "dp",
    param_specs=None,
    donate_batch: bool = False,
):
    """Jitted FM SGD step over COO batches; ONE fused (dtype-bucketed)
    in-graph psum on the mesh — the [F,K] factor grads, [F] linear grads
    and loss scalars cross ICI as a single contiguous f32 buffer.

    ``donate_batch=True`` (single-device path) donates params AND the
    batch arrays, the same contract as
    :func:`~dmlc_tpu.models.linear.make_linear_train_step`: XLA reuses
    the H2D landing buffers and updates the factor table in place —
    only for streaming callers that rebind params each step and never
    touch a batch after its step (DeviceFeed loops, FMLearner)."""
    check(num_features > 0, "num_features required")

    def _apply(params, gw, gb, gv, wsum):
        denom = jnp.maximum(wsum, 1e-12)
        return {
            "w": params["w"] - learning_rate * (gw / denom + l2 * params["w"]),
            "b": params["b"] - learning_rate * (gb / denom),
            "v": params["v"] - learning_rate * (gv / denom + l2 * params["v"]),
        }

    if mesh is None:

        def step(params, batch):
            gw, gb, gv, loss_sum, wsum = _fm_forward_grads(
                params, batch, objective, num_features
            )
            params = _apply(params, gw, gb, gv, wsum)
            return params, {"loss_sum": loss_sum, "weight_sum": wsum}

        fn = instrumented_jit(
            step, "fm.step",
            donate_argnums=(0, 1) if donate_batch else (),
        )
        return _suppress_donation_warnings(fn) if donate_batch else fn

    # Entries arrive SHARDED (ShardedCSRBatch: per-shard sections, local
    # row ids) — each device holds only its own nnz; no global mask.
    batch_specs = {
        "label": P(axis),
        "weight": P(axis),
        "indices": P(axis),
        "values": P(axis),
        "offsets": P(axis),
    }

    if param_specs is None:
        param_specs = match_partition_rules(
            FM_PARTITION_RULES,
            jax.eval_shape(lambda: init_fm_params(max(num_features, 1), 2)),
        )

    def _sharded(params, batch):
        gw, gb, gv, loss_sum, wsum = _fm_forward_grads(
            params, batch, objective, num_features
        )
        # gradients never round-trip through host numpy: one bucketed
        # in-graph psum carries the whole gradient pytree across ICI
        gw, gb, gv, loss_sum, wsum = bucketed_psum(
            (gw, gb, gv, loss_sum, wsum), axis=axis
        )
        params = _apply(params, gw, gb, gv, wsum)
        return params, {"loss_sum": loss_sum, "weight_sum": wsum}

    step = shard_map(
        _sharded, mesh=mesh,
        in_specs=(param_specs, batch_specs),
        out_specs=(param_specs, P()),
    )
    return instrumented_jit(step, "fm.step", donate_argnums=(0,))


class FMLearner:
    """uri → fitted FM params over a DeviceFeed (csr layout)."""

    def __init__(self, mesh: Optional[Mesh] = None, **hyper):
        self.param = FMParam()
        self.param.init(hyper)
        self.mesh = mesh
        self.params = None
        self._step = None
        self._nf = None
        self._unlisten = None
        if mesh is not None:
            import weakref

            from dmlc_tpu import collective

            ref = weakref.ref(self)

            def _membership_cb():
                learner = ref()
                if learner is not None and learner.params is not None:
                    learner.reshard()

            self._unlisten = collective.on_membership_change(_membership_cb)

    def _ensure(self, num_features: int):
        if self.params is None:
            nf = self.param.num_features or num_features
            self.params = init_fm_params(
                nf, self.param.num_factors, self.param.init_scale
            )
            self._nf = nf
            if self.mesh is not None:
                self.params = shard_params(
                    self.params, self.mesh, rules=FM_PARTITION_RULES
                )
        if self._step is None:
            self._step = make_fm_train_step(
                self.mesh,
                self._nf or self.param.num_features or num_features,
                objective=self.param.objective,
                learning_rate=self.param.learning_rate,
                l2=self.param.l2,
                # the fit loop rebinds params every step and never touches
                # a batch after its step — the donation contract holds
                donate_batch=self.mesh is None,
            )

    def reshard(self, mesh: Optional[Mesh] = None) -> None:
        """Elastic re-entry hook (see LinearLearner.reshard): re-place the
        factor table + linear weights on a mesh rebuilt over the current
        device set and drop the traced step."""
        if self.mesh is None or self.params is None:
            return
        if mesh is None:
            check(
                len(self.mesh.axis_names) == 1,
                "pass mesh= to reshard a multi-axis mesh",
            )
            mesh = Mesh(np.asarray(jax.devices()), self.mesh.axis_names)
        self.mesh = mesh
        self.params = shard_params(
            jax.device_get(self.params), mesh, rules=FM_PARTITION_RULES
        )
        self._step = None

    def fit_feed(self, feed, epochs: int = 1, log_every: int = 0,
                 snapshotter=None, start_epoch: int = 0, history=None):
        """Train over a csr DeviceFeed; ``log_every`` (epochs) also logs
        the feed's per-stage stall breakdown (device.feed.stall_breakdown).

        ``snapshotter``/``start_epoch``/``history`` follow the same
        preemption-proof contract as LinearLearner.fit_feed: epoch
        boundaries hand a state tree to the async snapshot writer, a
        preemption notice finalizes a just-in-time commit and raises
        ``Preempted`` (see docs/robustness.md "Preemption & resume")."""
        from dmlc_tpu.models.linear import EpochMetrics

        check(feed.spec.layout == "csr", "FM consumes csr batches")
        # see LinearLearner.fit_feed: mesh steps need the sharded layout
        check(
            getattr(feed, "_mesh", None) is self.mesh,
            "feed mesh and learner mesh must match (csr entry layouts "
            "differ between mesh and single-device runs)",
        )
        from dmlc_tpu import obs
        from dmlc_tpu.models.fitloop import FitLoopObs
        from dmlc_tpu.resilience import Preempted, preempt

        fl = FitLoopObs("fm")
        history = list(history) if history else []
        for epoch in range(start_epoch, epochs):
            acc = EpochMetrics()
            nstep = 0
            preempted = False
            t0 = time.monotonic_ns()
            with obs.span("epoch", model="fm", epoch=epoch):
                for batch in feed:
                    self._ensure(self.param.num_features)
                    with obs.span("train_step", model="fm", step=nstep):
                        obs.flow_step(obs.current_flow(), "chunk")
                        self.params, metrics = self._step(
                            self.params, step_batch(batch, "csr")
                        )
                    acc.add(metrics)
                    fl.note_step()
                    # every DMLC_TPU_STEP_SAMPLE_N-th step: one timed
                    # block_until_ready -> dmlc_step_device_ms (no sync
                    # on the other N-1 steps)
                    fl.sample_latency(metrics)
                    nstep += 1
                    if snapshotter is not None and preempt.poll():
                        preempted = True
                        break
            if preempted:
                snapshotter.finalize()
                raise Preempted(
                    "preempted in epoch %d after %d steps" % (epoch, nstep))
            loss = acc.mean_loss()
            history.append(loss)
            fl.end_epoch(
                epoch, nstep, t0, loss, feed=feed,
                log_every=log_every, params=self.params,
                snapshotter=snapshotter,
                snap_state=(None if snapshotter is None else
                            lambda e=epoch: self._snapshot_state(
                                feed, e, history)),
            )
            if epoch + 1 < epochs:
                feed.before_first()
        return history

    def _snapshot_state(self, feed, epoch: int, history) -> Dict:
        """Job-snapshot state tree at one epoch boundary (see
        LinearLearner._snapshot_state — FM has no velocity term)."""
        from dmlc_tpu.obs import audit

        state = {
            "model": {"params": dict(self.params)},
            "epoch": int(epoch),
            "history": [float(x) for x in history],
            "rng": None,
            "audit": audit.auditor().export_state(),
        }
        parser = getattr(feed, "_parser", None)
        if hasattr(parser, "snapshot_state"):
            state["data"] = {"parser": parser.snapshot_state()}
        return state

    def restore_snapshot_model(self, model: Dict) -> None:
        """Re-place a snapshot's host FM params on device (mesh-placed
        when this learner runs on a mesh)."""
        self.params = {k: jnp.asarray(v) for k, v in model["params"].items()}
        if self.mesh is not None:
            self.params = shard_params(
                self.params, self.mesh, rules=FM_PARTITION_RULES)

    def predict_batch(self, batch) -> np.ndarray:
        num_rows = int(batch["label"].shape[0])
        row_ids = expand_row_ids(batch["offsets"], batch["values"].shape[0])
        v_e = jnp.take(self.params["v"], batch["indices"], axis=0)
        xv = batch["values"][:, None] * v_e
        s = jax.ops.segment_sum(xv, row_ids, num_segments=num_rows)
        q = jax.ops.segment_sum(xv * xv, row_ids, num_segments=num_rows)
        linear = spmv(
            batch["values"], batch["indices"], row_ids,
            self.params["w"], num_rows,
        )
        return np.asarray(
            self.params["b"] + linear + 0.5 * jnp.sum(s * s - q, axis=-1)
        )
