"""Linear learners with data-parallel psum gradient sync.

This is the BASELINE north-star model: ``libsvm file → InputSplit(part=host)
→ parser → device batch → psum(grad) → SGD`` (SURVEY §7 minimum end-to-end
slice). The reference has no learners; this is the allreduce-SGD loop its
downstream (rabit-based) consumers run, built TPU-first:

- the train step is one jitted shard_map over the mesh: local forward +
  gradient, one fused psum per step (large fused buckets are what push ICI
  utilization up — SURVEY §7 hard parts), parameters replicated and donated
- deterministic f32 accumulation: per-shard sums then a single psum, so the
  reduction order is fixed and CPU-vs-TPU runs are comparable bit-for-bit at
  the f32 level
- dense layout for small feature spaces (HIGGS: one [B,F]·[F] matvec on the
  MXU) and COO/segment-sum for sparse (dmlc_tpu.ops.spmv)
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dmlc_tpu.utils.jax_compat import shard_map

from dmlc_tpu.collective.device import bucketed_psum
from dmlc_tpu.obs.device_telemetry import instrumented_jit
from dmlc_tpu.ops.objectives import margin_loss_grad
from dmlc_tpu.ops.spmv import expand_row_ids, spmv, spmv_transpose
from dmlc_tpu.parallel.partition import match_partition_rules, shard_params
from dmlc_tpu.params.parameter import Parameter, field
from dmlc_tpu.utils.logging import DMLCError, check


class LinearModelParam(Parameter):
    """Hyper-parameters (a dmlc Parameter struct, parameter.h style)."""

    objective = field(
        str,
        "logistic",
        description="Loss: logistic (labels 0/1), squared, or hinge (0/1).",
    )
    learning_rate = field(float, 0.1, lower_bound=0.0)
    l2 = field(float, 0.0, lower_bound=0.0, description="L2 penalty on w.")
    momentum = field(float, 0.0, lower_bound=0.0, upper_bound=1.0)
    num_features = field(int, 0, description="Feature dim (0 = infer).")


_DENSE_KEYS = ("x", "label", "weight")
_CSR_KEYS = ("label", "weight", "indices", "values", "offsets")


def step_batch(batch: Dict, layout: str) -> Dict:
    """Strip DeviceFeed metadata (num_rows/num_nonzero ints) down to the
    array fields a jitted train step consumes."""
    keys = _DENSE_KEYS if layout == "dense" else _CSR_KEYS
    return {k: batch[k] for k in keys}


def init_linear_params(num_features: int, dtype=jnp.float32) -> Dict:
    """{"w": [F], "b": scalar} — replicated across the mesh."""
    return {
        "w": jnp.zeros((num_features,), dtype=dtype),
        "b": jnp.zeros((), dtype=dtype),
    }


#: Data-parallel placement for {"w": [F], "b": scalar}: everything
#: replicated — only the BATCH shards over the mesh, and the in-graph
#: psum lands identical grads on every device. Declared as a regex
#: partition-rule table (parallel/partition.py) so the placement is
#: data, linted by scripts/check_partition_rules.py, instead of being
#: hard-coded into the step builder.
LINEAR_PARTITION_RULES = ((r"^(w|b)$", P()),)

#: Feature-sharded (dp×mp) placement: the weight vector splits over the
#: model axis (make_feature_sharded_train_step's layout).
LINEAR_MP_PARTITION_RULES = ((r"^w$", P("mp")), (r"^b$", P()))


def linear_predict_dense(params: Dict, x):
    return x @ params["w"] + params["b"]


def _margin_grad(objective: str, margin, label):
    """Per-row (loss, dloss/dmargin) — shared with the Pallas fused kernel
    (ops/objectives.py holds the single definition)."""
    try:
        return margin_loss_grad(objective, margin, label)
    except ValueError as err:
        raise DMLCError(str(err)) from err


def _suppress_donation_warnings(step):
    """Batch leaves ([B,F] x, per-entry arrays) can never alias a donating
    step's outputs (w [F], scalars), so XLA warns "donated buffers were
    not usable" per compiled shape — the donation is still worth it for
    the early buffer release. The suppression is scoped to THIS step's
    call sites via catch_warnings, not installed process-globally: a
    user's own jitted function emitting the same message may be flagging
    a real missed donation, and this package must not eat that signal.

    The warnings fire only at trace/compile time (once per argument-shape
    signature), so the suppression engages only on calls with an unseen
    signature: steady-state steps call straight through — no per-step
    catch_warnings, whose filter-version bump would invalidate every
    module's __warningregistry__ and make unrelated once-per-location
    warnings re-fire each iteration. (catch_warnings swaps the global
    filter list for the compile call's duration; the swap is not atomic
    across threads — the stdlib limitation — but the window is one
    compile, not every step.)"""
    import functools
    import warnings

    seen = set()

    @functools.wraps(step)
    def wrapped(*args, **kwargs):
        key = tuple(
            (getattr(x, "shape", None), str(getattr(x, "dtype", type(x))))
            for x in jax.tree_util.tree_leaves((args, kwargs))
        )
        if key in seen:
            return step(*args, **kwargs)
        seen.add(key)
        with warnings.catch_warnings():
            for msg in ("Some donated buffers were not usable",
                        "Donation is not implemented"):
                warnings.filterwarnings("ignore", message=msg)
            return step(*args, **kwargs)

    return wrapped


def _resolve_pallas(use_pallas: Optional[bool], layout: str,
                    objective: str):
    """Validate + default the Pallas kernel switch (env
    DMLC_TPU_PALLAS=1); shared by the mesh and hostsync step builders.

    Returns the kernel MODE, not a bare bool: False (off), "dense" (the
    fused whole-step kernel, dense layout), or "spmv" (the COO
    segment-sum kernel on the csr margin path; the feature-direction
    scatter stays on XLA). Truthiness is preserved, so boolean callers
    keep working."""
    if use_pallas is None:
        import os

        use_pallas = os.environ.get("DMLC_TPU_PALLAS", "0") == "1"
    if not use_pallas:
        return False
    from dmlc_tpu.ops import pallas_kernels
    from dmlc_tpu.ops.objectives import OBJECTIVES

    if layout == "dense":
        check(
            pallas_kernels.available and objective in OBJECTIVES,
            "pallas path unavailable for this configuration",
        )
        return "dense"
    check(pallas_kernels.available,
          "pallas path unavailable for this configuration")
    return "spmv"


def _build_local_grads(objective: str, layout: str, num_features: int,
                       use_pallas: bool):
    """The per-shard gradient core: f(params, batch) -> (gw, gb, loss_sum,
    weight_sum), no cross-device communication. ONE definition feeds every
    sync flavor — the in-graph SPMD step, the single-device step, and the
    legacy host-allreduce twin — so their local math is identical by
    construction (the parity suites lean on this)."""
    # Mosaic only targets TPU; elsewhere (CPU meshes in tests, the
    # dryrun_multichip virtual devices) the kernel runs interpreted.
    pallas_interpret = jax.default_backend() != "tpu"

    def _local_grads(params, batch):
        label = batch["label"]
        weight = batch["weight"]
        if layout == "dense" and use_pallas:
            from dmlc_tpu.ops.pallas_kernels import fused_linear_grads

            gw, gb, loss_sum, wsum = fused_linear_grads(
                batch["x"], label, weight, params["w"], params["b"],
                objective=objective, interpret=pallas_interpret,
            )
            # the kernel computes in f32; keep the params dtype contract of
            # the XLA path (no silent upcast of bf16 params mid-training)
            return (gw.astype(params["w"].dtype),
                    gb.astype(params["b"].dtype), loss_sum, wsum)
        if layout == "dense":
            margin = batch["x"] @ params["w"] + params["b"]
        else:
            # the batch carries CSR offsets (small H2D payload); expand to
            # per-entry row ids here, on device. Under the mesh shard_map
            # the shapes are per-shard local, so the same expansion yields
            # local row ids from the shard's local offsets.
            row_ids = expand_row_ids(
                batch["offsets"], batch["values"].shape[0]
            )
            if use_pallas == "spmv":
                from dmlc_tpu.ops.spmv import spmv_pallas

                margin = spmv_pallas(
                    batch["values"], batch["indices"], row_ids,
                    params["w"], label.shape[0],
                    interpret=pallas_interpret,
                ) + params["b"]
            else:
                margin = spmv(
                    batch["values"],
                    batch["indices"],
                    row_ids,
                    params["w"],
                    label.shape[0],
                ) + params["b"]
        loss, gmargin = _margin_grad(objective, margin, label)
        wg = weight * gmargin
        if layout == "dense":
            gw = batch["x"].T @ wg
        else:
            gw = spmv_transpose(
                batch["values"], batch["indices"], row_ids, wg,
                num_features,
            )
        gb = jnp.sum(wg)
        loss_sum = jnp.sum(weight * loss)
        weight_sum = jnp.sum(weight)
        return gw, gb, loss_sum, weight_sum

    return _local_grads


def _build_apply(learning_rate: float, l2: float, momentum: float):
    """The SGD update: f(params, velocity, gw, gb, wsum) with the grads
    already reduced. Shared across sync flavors like _build_local_grads."""

    def _apply(params, velocity, gw, gb, wsum):
        denom = jnp.maximum(wsum, 1e-12)
        gw = gw / denom + l2 * params["w"]
        gb = gb / denom
        if momentum > 0.0:
            velocity = {
                "w": momentum * velocity["w"] + gw,
                "b": momentum * velocity["b"] + gb,
            }
            gw, gb = velocity["w"], velocity["b"]
        params = {
            "w": params["w"] - learning_rate * gw,
            "b": params["b"] - learning_rate * gb,
        }
        return params, velocity

    return _apply


def make_linear_train_step(
    mesh: Optional[Mesh],
    objective: str = "logistic",
    learning_rate: float = 0.1,
    l2: float = 0.0,
    momentum: float = 0.0,
    layout: str = "dense",
    num_features: int = 0,
    axis: str = "dp",
    use_pallas: Optional[bool] = None,
    donate_batch: bool = False,
    param_specs=None,
):
    """Build the jitted allreduce-SGD step.

    Returns step(params, velocity, batch) -> (params, velocity, metrics)
    where metrics = {"loss_sum": Σ w·loss, "weight_sum": Σ w} (host divides).
    With ``mesh`` the batch is consumed sharded over ``axis`` and gradients
    cross ICI in one fused psum; without, it is a single-device step.

    ``axis`` may be a tuple of mesh axis names for hybrid data
    parallelism — e.g. ``("dcn", "dp")`` on a
    :func:`~dmlc_tpu.parallel.make_multislice_mesh` shards batch rows over
    slices × chips and the psum lowers to a per-slice ICI reduction plus
    one small cross-slice DCN exchange (outer axis = slices).

    ``use_pallas`` (default: env DMLC_TPU_PALLAS=1) routes the dense
    gradient core through the fused Pallas kernel
    (ops/pallas_kernels.fused_linear_grads); on the csr layout it routes
    the margin SpMV's row reduce through the COO segment-sum kernel
    (ops/spmv.spmv_pallas) while the feature-direction scatter stays on
    XLA. Measured at parity with XLA's own fusion on v5e (BASELINE.md) —
    XLA stays the default.

    ``donate_batch=True`` donates ALL step inputs — params, velocity, and
    the batch arrays: the H2D landing buffers are released to XLA the
    moment the step consumes them (HBM headroom for the next in-flight
    transfer — SURVEY §7 hard parts: donation) and the parameter update
    is in-place. Only for streaming callers that rebind params/velocity
    each step and never touch a batch after its step (DeviceFeed loops,
    the bench tiers, LinearLearner); reusing a donated input afterward is
    an error by design. Default False keeps every input alive (the mesh
    path has always donated params/velocity — that is unchanged).
    """
    check(layout in ("dense", "csr"), "layout must be dense or csr")
    if layout == "csr":
        check(num_features > 0, "csr layout requires num_features")
    use_pallas = _resolve_pallas(use_pallas, layout, objective)
    _local_grads = _build_local_grads(objective, layout, num_features,
                                      use_pallas)
    _apply = _build_apply(learning_rate, l2, momentum)

    if mesh is None:

        def step(params, velocity, batch):
            gw, gb, loss_sum, wsum = _local_grads(params, batch)
            params, velocity = _apply(params, velocity, gw, gb, wsum)
            return params, velocity, {"loss_sum": loss_sum, "weight_sum": wsum}

        # this path historically donated nothing — donation here is purely
        # opt-in (tests and notebooks legitimately reuse inputs)
        fn = instrumented_jit(
            step, "linear.step",
            donate_argnums=(0, 1, 2) if donate_batch else (),
        )
        return _suppress_donation_warnings(fn) if donate_batch else fn

    # Mesh path: one shard_map; batch rows sharded, params replicated. The
    # csr layout ships SHARDED entries (ShardedCSRBatch: per-shard entry
    # sections with local row ids, device/csr.py), so each device receives
    # only its own nnz and the segment-sum is purely local — per-device
    # H2D ∝ global_nnz / world, the Criteo-scale contract.
    if layout == "dense":
        batch_specs = {
            "x": P(axis),
            "label": P(axis),
            "weight": P(axis),
        }
    else:
        batch_specs = {
            "label": P(axis),
            "weight": P(axis),
            "indices": P(axis),
            "values": P(axis),
            "offsets": P(axis),
        }

    # parameter placement as DATA: the rule table (or a caller-supplied
    # spec tree) drives both sides of the shard_map signature, so the
    # step's layout contract and shard_params' placement cannot drift
    if param_specs is None:
        template = jax.eval_shape(
            lambda: init_linear_params(max(num_features, 1))
        )
        param_specs = match_partition_rules(LINEAR_PARTITION_RULES, template)

    def _sharded(params, velocity, batch):
        gw, gb, loss_sum, wsum = _local_grads(params, batch)
        # ONE fused allreduce for everything that crosses ICI: grads and
        # the loss/weight scalars ride a single dtype-bucketed in-graph
        # psum (collective.bucketed_psum) — gradients never round-trip
        # through host numpy or collective.allreduce.
        gw, gb, loss_sum, wsum = bucketed_psum(
            (gw, gb, loss_sum, wsum), axis=axis
        )
        params, velocity = _apply(params, velocity, gw, gb, wsum)
        return params, velocity, {"loss_sum": loss_sum, "weight_sum": wsum}

    step = shard_map(
        _sharded,
        mesh=mesh,
        in_specs=(param_specs, param_specs, batch_specs),
        out_specs=(param_specs, param_specs, P()),
    )
    fn = instrumented_jit(
        step, "linear.step",
        donate_argnums=(0, 1, 2) if donate_batch else (0, 1),
    )
    return _suppress_donation_warnings(fn) if donate_batch else fn


def make_hostsync_train_step(
    objective: str = "logistic",
    learning_rate: float = 0.1,
    l2: float = 0.0,
    momentum: float = 0.0,
    layout: str = "dense",
    num_features: int = 0,
    use_pallas: Optional[bool] = None,
):
    """The legacy host-round-trip twin of the mesh SPMD step: local grads
    on device, ONE fused ``collective.allreduce`` over the active host
    engine (socket tree/ring on CPU clusters), apply on device.

    This is the rabit loop (examples/distributed_sgd.py) behind the
    step(params, velocity, batch) signature, and the ONLY sync flavor
    that works across socket-engine processes (no single ``Mesh`` spans
    them). It shares ``_build_local_grads``/``_build_apply`` with the
    SPMD step, and its reduction — one contiguous same-dtype buffer
    through the engine — mirrors ``bucketed_psum``'s bucket layout, so
    at world 2 (one addition per element on either path) the two sync
    flavors are bit-identical; the ci_checks.sh SPMD smoke pins that.
    In-mesh training should use :func:`make_linear_train_step` — see
    docs/distributed.md "Device collectives" for the migration note.
    """
    check(layout in ("dense", "csr"), "layout must be dense or csr")
    if layout == "csr":
        check(num_features > 0, "csr layout requires num_features")
    use_pallas = _resolve_pallas(use_pallas, layout, objective)
    local = instrumented_jit(
        _build_local_grads(objective, layout, num_features, use_pallas),
        "linear.hostsync_grads",
    )
    apply_fn = instrumented_jit(
        _build_apply(learning_rate, l2, momentum), "linear.hostsync_apply"
    )

    def step(params, velocity, batch):
        from dmlc_tpu import collective

        gw, gb, loss_sum, wsum = local(params, batch)
        gw_h = np.asarray(gw)
        scalars = np.asarray(
            [gb, loss_sum, wsum], dtype=gw_h.dtype
        )
        # one fused buffer = one allreduce per step, the same bucket
        # layout bucketed_psum traces in-graph
        reduced = collective.allreduce(
            np.concatenate([gw_h.ravel(), scalars])
        )
        gw_r = jnp.asarray(reduced[: gw_h.size].reshape(gw_h.shape))
        gb_r = jnp.asarray(reduced[gw_h.size])
        wsum_r = jnp.asarray(reduced[gw_h.size + 2])
        params, velocity = apply_fn(params, velocity, gw_r, gb_r, wsum_r)
        return params, velocity, {
            "loss_sum": reduced[gw_h.size + 1],
            "weight_sum": reduced[gw_h.size + 2],
        }

    return step


def make_feature_sharded_train_step(
    mesh: Mesh,
    objective: str = "logistic",
    learning_rate: float = 0.1,
    batch_axis: str = "dp",
    feature_axis: str = "mp",
):
    """dp×mp train step: batch rows sharded over ``batch_axis``, the weight
    vector (and the feature dim of x) sharded over ``feature_axis``.

    This is the TPU-native analog of the reference's parameter-server mode
    (PARITY §2.9): parameter state lives sharded across devices instead of
    on server processes, and the "push/pull" is XLA collectives — a psum of
    partial margins over ``feature_axis`` (the pull of the full model
    response) and a psum of gradients over ``batch_axis`` (the push of data
    shards' updates). Only mp-invariant scalars and [B/dp] vectors cross
    ICI; the [F/mp] gradient never leaves its shard.

    Layouts (global shapes): x [B, F] sharded (dp, mp); label/weight [B]
    sharded (dp); params {"w": [F] sharded (mp), "b": replicated}.
    Returns (step, in_shardings) where in_shardings maps example arrays to
    ``NamedSharding``s for ``jax.device_put``.
    """
    dp = batch_axis
    mp = feature_axis
    # the canonical axis name resolves through the linted rule table; a
    # custom feature_axis keeps the same shape with the name swapped in
    if mp == "mp":
        param_specs = match_partition_rules(
            LINEAR_MP_PARTITION_RULES,
            jax.eval_shape(lambda: init_linear_params(2)),
        )
    else:
        param_specs = {"w": P(mp), "b": P()}

    def _step(params, batch_x, batch_y, batch_w):
        # local shapes: x [B/dp, F/mp], w [F/mp]
        partial_margin = batch_x @ params["w"]
        margin = jax.lax.psum(partial_margin, mp) + params["b"]
        loss, dmargin = _margin_grad(objective, margin, batch_y)
        wg = batch_w * dmargin
        # margin is mp-invariant, so wg is too: gw needs only the dp-psum
        gw = jax.lax.psum(batch_x.T @ wg, dp)
        gb = jax.lax.psum(jnp.sum(wg), dp)
        wsum = jax.lax.psum(jnp.sum(batch_w), dp)
        loss_sum = jax.lax.psum(jnp.sum(batch_w * loss), dp)
        denom = jnp.maximum(wsum, 1e-12)
        new_params = {
            "w": params["w"] - learning_rate * gw / denom,
            "b": params["b"] - learning_rate * gb / denom,
        }
        return new_params, {"loss_sum": loss_sum, "weight_sum": wsum}

    step = instrumented_jit(
        shard_map(
            _step,
            mesh=mesh,
            in_specs=(param_specs, P(dp, mp), P(dp), P(dp)),
            out_specs=(param_specs, P()),
        ),
        "linear.step_mp",
        donate_argnums=(0,),
    )
    in_shardings = {
        "x": NamedSharding(mesh, P(dp, mp)),
        "label": NamedSharding(mesh, P(dp)),
        "weight": NamedSharding(mesh, P(dp)),
        "w": NamedSharding(mesh, P(mp)),
        "b": NamedSharding(mesh, P()),
    }
    return step, in_shardings


class EpochMetrics:
    """Collect per-step device metric scalars with no per-step dispatch or
    host sync; reading does one batched device_get. Shared by the learners'
    fit loops (a per-step ``float()`` stalls the feed's batch-in-flight
    overlap; a per-step device add pays dispatch overhead per step)."""

    def __init__(self):
        self._loss = []
        self._weight = []
        self._loss_total = 0.0
        self._weight_total = 0.0

    def add(self, metrics: Dict) -> None:
        self._loss.append(metrics["loss_sum"])
        self._weight.append(metrics["weight_sum"])

    def mean_loss(self) -> float:
        if self._loss:
            # drain pending scalars into the running totals so repeated
            # reads (log_every) never re-fetch what was already summed
            loss, weight = jax.device_get((self._loss, self._weight))
            self._loss_total += float(np.sum(loss))
            self._weight_total += float(np.sum(weight))
            self._loss.clear()
            self._weight.clear()
        return self._loss_total / max(self._weight_total, 1e-12)


class LinearLearner:
    """Convenience trainer: uri → fitted params (the rabit-SGD loop).

    ``sync`` picks the gradient-reduction flavor:

    - ``"spmd"`` (default): the in-graph path — params live mesh-placed
      (``shard_params`` over ``LINEAR_PARTITION_RULES``), the batch
      shards over the mesh, and the allreduce is a bucketed psum traced
      INSIDE the jitted step. Gradients never touch host numpy.
    - ``"host"``: the legacy rabit loop (``make_hostsync_train_step``) —
      the cross-host fallback when the socket engine spans processes no
      single Mesh can.

    A mesh learner registers a ``collective.on_membership_change``
    listener: elastic re-entry / recovery re-places its params on a mesh
    rebuilt over the surviving devices (:meth:`reshard`).
    """

    def __init__(self, mesh: Optional[Mesh] = None, sync: str = "spmd",
                 **hyper):
        check(sync in ("spmd", "host"), "sync must be spmd or host")
        self.param = LinearModelParam()
        self.param.init(hyper)
        self.mesh = mesh
        self.sync = sync
        self.params = None
        self.velocity = None
        self._step = None
        self._layout = None
        self._nf = None
        self._unlisten = None
        if mesh is not None:
            import weakref

            from dmlc_tpu import collective

            ref = weakref.ref(self)

            def _membership_cb():
                learner = ref()
                if learner is not None and learner.params is not None:
                    learner.reshard()

            self._unlisten = collective.on_membership_change(_membership_cb)

    def _ensure(self, num_features: int, layout: str):
        if self.params is None:
            nf = self.param.num_features or num_features
            self.params = init_linear_params(nf)
            self.velocity = {
                "w": jnp.zeros_like(self.params["w"]),
                "b": jnp.zeros_like(self.params["b"]),
            }
            self._layout = layout
            self._nf = nf
            if self.mesh is not None and self.sync == "spmd":
                # params live mesh-placed from step zero: the traced step
                # consumes committed arrays, no per-call resharding
                self.params = shard_params(
                    self.params, self.mesh, rules=LINEAR_PARTITION_RULES
                )
                self.velocity = shard_params(
                    self.velocity, self.mesh, rules=LINEAR_PARTITION_RULES
                )
        if self._step is None:
            if self._layout is None:
                # params came from load(): derive what init skipped
                self._layout = layout
                self._nf = (self.param.num_features or num_features
                            or int(self.params["w"].shape[0]))
            if self.sync == "host":
                self._step = make_hostsync_train_step(
                    objective=self.param.objective,
                    learning_rate=self.param.learning_rate,
                    l2=self.param.l2,
                    momentum=self.param.momentum,
                    layout=self._layout,
                    num_features=self._nf,
                )
            else:
                self._step = make_linear_train_step(
                    self.mesh,
                    objective=self.param.objective,
                    learning_rate=self.param.learning_rate,
                    l2=self.param.l2,
                    momentum=self.param.momentum,
                    layout=self._layout,
                    num_features=self._nf,
                    donate_batch=True,  # fit_feed consumes batches once
                )

    def reshard(self, mesh: Optional[Mesh] = None) -> None:
        """Re-place params/velocity on ``mesh`` (default: a fresh mesh
        over the CURRENT device set, same axis names) and drop the traced
        step — the elastic re-entry hook. Leaves round-trip through host
        copies because the old placement may reference devices that no
        longer exist."""
        if self.mesh is None or self.params is None:
            return
        if mesh is None:
            check(
                len(self.mesh.axis_names) == 1,
                "pass mesh= to reshard a multi-axis mesh",
            )
            mesh = Mesh(np.asarray(jax.devices()), self.mesh.axis_names)
        self.mesh = mesh
        self.params = shard_params(
            jax.device_get(self.params), mesh, rules=LINEAR_PARTITION_RULES
        )
        if self.velocity is not None:
            self.velocity = shard_params(
                jax.device_get(self.velocity), mesh,
                rules=LINEAR_PARTITION_RULES,
            )
        self._step = None  # retrace against the new mesh on next batch

    def fit_uri(
        self,
        uri: str,
        batch_size: int = 4096,
        epochs: int = 1,
        layout: str = "dense",
        num_features: int = 0,
        part_index: Optional[int] = None,
        num_parts: Optional[int] = None,
        drop_remainder: bool = False,
        log_every: int = 0,
        snapshot_uri: Optional[str] = None,
        resume: bool = False,
        snap_every_epochs: int = 1,
    ):
        """One call from data URI to fitted params: InputSplit part →
        parser → DeviceFeed → fit_feed. The part defaults to this
        worker's collective rank/world (each worker reads its own byte
        range — the reference's ``InputSplit::Create(uri, rank, world)``
        contract), so the same line works single-process, on a mesh, or
        under dmlc-submit with the socket engine.

        ``snapshot_uri`` arms preemption-proof job snapshots: every
        ``snap_every_epochs`` epoch boundary (plus the
        ``DMLC_TPU_SNAP_EVERY_S`` wall-clock trigger) commits model +
        optimizer + read-plan + audit state through the async
        two-phase-commit writer, and a SIGTERM mid-epoch finalizes a
        just-in-time snapshot and exits with the relaunch code.
        ``resume=True`` loads the newest committed snapshot first: the
        model restores, the shuffle re-derives the interrupted epoch
        permutation, the audit chains re-arm, and training continues at
        the next epoch — bit-identical to a run that was never killed
        (see docs/robustness.md "Preemption & resume")."""
        from dmlc_tpu import collective
        from dmlc_tpu.data import create_parser
        from dmlc_tpu.device import BatchSpec, DeviceFeed

        nf = num_features or self.param.num_features
        check(nf > 0, "fit_uri requires num_features")
        if part_index is None:
            part_index = collective.rank()
        if num_parts is None:
            num_parts = collective.world_size()
        feed = DeviceFeed(
            create_parser(uri, part_index, num_parts),
            BatchSpec(batch_size=batch_size, layout=layout,
                      num_features=nf, drop_remainder=drop_remainder),
            mesh=self.mesh,
        )
        if snapshot_uri is None:
            check(not resume, "resume=True requires snapshot_uri")
            return self.fit_feed(feed, epochs=epochs, log_every=log_every)
        from dmlc_tpu.collective import JobSnapshot, Snapshotter, \
            load_snapshot

        snap = JobSnapshot(snapshot_uri, rank=collective.rank(),
                           world_size=collective.world_size())
        start_epoch = 0
        history = None
        snapshotter = Snapshotter(snap, every_epochs=snap_every_epochs)
        try:
            if resume:
                version, state, _meta = load_snapshot(snap)
                if version and state is not None:
                    self._restore_snapshot_model(state["model"])
                    start_epoch = int(state.get("epoch", -1)) + 1
                    history = list(state.get("history", ()))
                    pst = (state.get("data") or {}).get("parser")
                    parser = getattr(feed, "_parser", None)
                    if pst and hasattr(parser, "restore_state"):
                        parser.restore_state(pst)
                    snapshotter.mark_restored(start_epoch - 1)
            return self.fit_feed(
                feed, epochs=epochs, log_every=log_every,
                snapshotter=snapshotter, start_epoch=start_epoch,
                history=history,
            )
        finally:
            snapshotter.close()

    def _restore_snapshot_model(self, model: Dict) -> None:
        """Re-place a snapshot's host model/optimizer state on device
        (mesh-placed when this learner runs spmd on a mesh)."""
        self.params = {k: jnp.asarray(v) for k, v in model["params"].items()}
        velocity = model.get("velocity")
        if velocity is not None:
            self.velocity = {k: jnp.asarray(v) for k, v in velocity.items()}
        if self.mesh is not None and self.sync == "spmd":
            self.params = shard_params(
                self.params, self.mesh, rules=LINEAR_PARTITION_RULES)
            if self.velocity is not None:
                self.velocity = shard_params(
                    self.velocity, self.mesh, rules=LINEAR_PARTITION_RULES)

    def fit_feed(self, feed, epochs: int = 1, log_every: int = 0,
                 snapshotter=None, start_epoch: int = 0, history=None):
        """Train over a DeviceFeed for N epochs; returns per-epoch losses.

        With ``snapshotter`` armed the loop polls for preemption notices
        between steps (SIGTERM via resilience/preempt.py, or the
        injectable ``preempt.notice`` faultpoint): a notice stops the
        partial epoch, finalizes the freshest epoch-boundary snapshot
        within the grace window, and raises
        :class:`~dmlc_tpu.resilience.Preempted` so the process exits
        with the launcher's relaunch code. ``start_epoch``/``history``
        continue a resumed run (the returned history covers ALL epochs,
        restored ones included)."""
        from dmlc_tpu.utils.logging import log_info

        layout = feed.spec.layout
        # mesh csr steps consume the SHARDED entry layout (local row ids);
        # a feed built without the mesh would deliver replicated entries
        # whose global row ids silently corrupt every shard's segment-sum
        check(
            getattr(feed, "_mesh", None) is self.mesh,
            "feed mesh and learner mesh must match (csr entry layouts "
            "differ between mesh and single-device runs)",
        )
        from dmlc_tpu import obs
        from dmlc_tpu.models.fitloop import FitLoopObs
        from dmlc_tpu.resilience import Preempted, preempt

        fl = FitLoopObs("linear")
        history = list(history) if history else []
        for epoch in range(start_epoch, epochs):
            acc = EpochMetrics()
            nstep = 0
            preempted = False
            t0 = time.monotonic_ns()
            with obs.span("epoch", model="linear", epoch=epoch):
                for batch in feed:
                    self._ensure(feed.spec.num_features, layout)
                    # train_step closes the chunk's arrow chain: the feed
                    # set the thread's current flow around this yield
                    with obs.span("train_step", model="linear", step=nstep):
                        obs.flow_step(obs.current_flow(), "chunk")
                        self.params, self.velocity, metrics = self._step(
                            self.params, self.velocity,
                            step_batch(batch, layout)
                        )
                    acc.add(metrics)
                    fl.note_step()
                    # every DMLC_TPU_STEP_SAMPLE_N-th step: one timed
                    # block_until_ready -> dmlc_step_device_ms (no sync
                    # on the other N-1 steps)
                    fl.sample_latency(metrics)
                    nstep += 1
                    if log_every and nstep % log_every == 0:
                        log_info(
                            "epoch %d step %d loss %.6f",
                            epoch, nstep, acc.mean_loss(),
                        )
                    if snapshotter is not None and preempt.poll():
                        preempted = True
                        break
            if preempted:
                # a partial epoch is never snapshotted (resume replays it
                # in full — that is what keeps the relaunch bit-identical);
                # commit the freshest epoch-boundary capture and exit with
                # the relaunch code
                snapshotter.finalize()
                raise Preempted(
                    "preempted in epoch %d after %d steps; last committed "
                    "snapshot epoch %d"
                    % (epoch, nstep, snapshotter.committed_epoch))
            loss = acc.mean_loss()
            history.append(loss)
            fl.end_epoch(
                epoch, nstep, t0, loss, feed=feed,
                log_every=log_every, params=self.params,
                snapshotter=snapshotter,
                snap_state=(None if snapshotter is None else
                            lambda e=epoch: self._snapshot_state(
                                feed, e, history)),
            )
            if epoch + 1 < epochs:
                feed.before_first()
        return history

    def _snapshot_state(self, feed, epoch: int, history) -> Dict:
        """The job-snapshot state tree at one epoch boundary (built on
        the training thread; the snapshotter host-copies it before the
        next epoch's donating steps run)."""
        from dmlc_tpu.obs import audit

        state = {
            "model": {"params": dict(self.params),
                      "velocity": dict(self.velocity or {})},
            "epoch": int(epoch),
            "history": [float(x) for x in history],
            "rng": None,  # SGD path draws no step-time randomness
            "audit": audit.auditor().export_state(),
        }
        parser = getattr(feed, "_parser", None)
        if hasattr(parser, "snapshot_state"):
            state["data"] = {"parser": parser.snapshot_state()}
        return state

    def predict(self, x: np.ndarray) -> np.ndarray:
        check(self.params is not None, "model not fitted")
        return np.asarray(linear_predict_dense(self.params, jnp.asarray(x)))

    # ---- checkpointing via the Stream surface (SURVEY §5.4) -------------
    def save(self, uri: str) -> None:
        from dmlc_tpu.io.filesystem import create_stream
        from dmlc_tpu.io.serializer import save_obj

        with create_stream(uri, "w") as out:
            save_obj(
                out,
                {
                    "param": self.param.to_dict(),
                    "w": np.asarray(self.params["w"]),
                    "b": np.asarray(self.params["b"]),
                },
            )

    def load(self, uri: str) -> None:
        from dmlc_tpu.io.filesystem import create_stream
        from dmlc_tpu.io.serializer import load_obj

        with create_stream(uri, "r") as stream:
            payload = load_obj(stream)
        self.param.init(payload["param"], allow_unknown=True)
        self.params = {
            "w": jnp.asarray(payload["w"]),
            "b": jnp.asarray(payload["b"]),
        }
