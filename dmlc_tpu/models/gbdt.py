"""Histogram gradient-boosted decision trees with psum histogram sync.

dmlc-core exists to serve xgboost: the reference's RowBlock feeds xgboost's
hist updater, and the tracker's tree+ring topology (reference
tracker/dmlc_tracker/tracker.py:185-252) was built so rabit could allreduce
per-node gradient histograms across workers. This module is that workload
rebuilt TPU-first — the one model family a reference user most expects to
find:

- **quantile binning** on device: features → uint8 bin ids once, up front
  (xgboost's hist trick — split finding then never touches floats)
- **level-wise growth with static shapes**: a depth-D tree is a complete
  binary tree; level ℓ builds one [2^ℓ, F, n_bins, 2] (grad, hess)
  histogram by segment-sum, finds every node's best split with cumsum +
  argmax (pure vectorized XLA, no data-dependent control flow), and
  descends sample node ids with one gather — every array shape is a
  function of (D, F, n_bins) only, so the whole tree build jits once
- **rabit's allreduce, as psum**: under a mesh the samples are sharded over
  ``axis``; each shard segment-sums its local histogram and ONE fused psum
  per level syncs (grad, hess) across ICI — byte-for-byte the collective
  pattern rabit runs for distributed xgboost, with the socket tree replaced
  by XLA's all-reduce. Split finding afterwards is replicated determinism:
  every shard sees identical histograms and picks identical splits, so no
  further communication crosses the mesh until the next level's histogram.
- deterministic accumulation: per-shard sums then one psum — fixed
  reduction order, comparable across backends (SURVEY §7 hard parts).

Inference is the same complete-tree descent: D gathers per tree, no
branches, vmapped over trees.

Scoping note (a deliberate semantic difference from xgboost): absent
entries in sparse input densify to 0.0 and bin like any value — there is
no learned per-node default direction for missing values (xgboost's
sparsity-aware split). Dense numeric data behaves identically; highly
sparse data where absence is informative will split differently. NaNs in
dense input land in the last bin (searchsorted semantics), not a
dedicated missing bin.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dmlc_tpu.utils.jax_compat import shard_map

from dmlc_tpu.obs.device_telemetry import instrumented_jit
from dmlc_tpu.params.parameter import Parameter, field
from dmlc_tpu.utils.logging import check


class GBDTParam(Parameter):
    """Hyper-parameters (a dmlc Parameter struct, parameter.h style)."""

    objective = field(
        str, "logistic",
        description="Loss: logistic (labels 0/1), squared, or softmax "
                    "(labels are class ids; set num_class).",
    )
    num_class = field(
        int, 0, lower_bound=0,
        description="Class count for objective=softmax (>= 2); 0 for "
                    "scalar objectives.",
    )
    num_trees = field(int, 20, lower_bound=1)
    max_depth = field(int, 6, lower_bound=1, upper_bound=12)
    learning_rate = field(float, 0.3, lower_bound=0.0)
    num_bins = field(
        int, 256, lower_bound=2, upper_bound=65536,
        description="Histogram bins per feature (255 cut points).",
    )
    reg_lambda = field(
        float, 1.0, lower_bound=0.0,
        description="L2 regularization on leaf values (xgboost lambda).",
    )
    min_child_weight = field(
        float, 1.0, lower_bound=0.0,
        description="Minimum hessian sum in a child for a split to count.",
    )
    subsample = field(
        float, 1.0, lower_bound=0.0, upper_bound=1.0,
        description="Per-tree row subsampling rate (stochastic gradient "
                    "boosting; bernoulli mask on (g, h)).",
    )
    colsample_bytree = field(
        float, 1.0, lower_bound=0.0, upper_bound=1.0,
        description="Per-tree feature subsampling rate (ceil(c*F) "
                    "features drawn without replacement).",
    )
    seed = field(
        int, 0,
        description="PRNG seed for subsample/colsample masks "
                    "(deterministic per (seed, tree)).",
    )


# ---------------------------------------------------------------------------
# binning
# ---------------------------------------------------------------------------


def fit_bins(x, num_bins: int = 256) -> np.ndarray:
    """Per-feature quantile cut points → edges [F, num_bins-1] (f32).

    Bin b holds values in (edges[b-1], edges[b]]; ids are produced by
    ``searchsorted(edges, x)`` so they always land in [0, num_bins).
    Mirrors xgboost's sketch → cut conversion at demo fidelity (exact
    quantiles of the supplied sample rather than a streaming sketch).

    On an accelerator backend the [N, F] quantile computes on device
    (the sort is the expensive part; on-chip it's ~free while the host
    quantile was the single biggest stage of a TPU fit) and only the
    tiny [F, num_bins-1] cut matrix comes back for the monotonic fixup.
    On the cpu backend numpy's introselect-based quantile beats an XLA
    full sort, so it stays host-side. ``x`` may already be a device
    array — the accelerator path then skips the H2D.
    """
    if not isinstance(x, jax.Array):
        x = np.asarray(x, dtype=np.float32)
    check(x.ndim == 2, "fit_bins expects [N, F]")
    qs = np.linspace(0.0, 1.0, num_bins + 1)[1:-1]
    if jax.default_backend() != "cpu":
        q = jnp.quantile(jnp.asarray(x, dtype=jnp.float32),
                         jnp.asarray(qs, dtype=jnp.float32), axis=0)
        edges = np.asarray(q).T.astype(np.float32)  # tiny D2H
    else:
        edges = np.quantile(
            np.asarray(x, dtype=np.float32), qs, axis=0
        ).T.astype(np.float32)  # [F, B-1]
    # strictly increasing edges keep searchsorted stable when a feature has
    # few distinct values (ties collapse quantiles to equal cut points).
    # The sequential recurrence e[b] = max(e[b], e[b-1] + d[b-1]) with
    # d = 4·eps·max(|e|, 1) is solved in closed form: with c = exclusive
    # cumsum of d, substituting f[b] = e[b] − c[b] turns it into
    # f[b] = max(f[b], f[b-1]), i.e. a running maximum — one vector pass
    # instead of a per-bin host loop (which dominated fit_bins for wide
    # feature spaces). float64 keeps the tiny increments from rounding
    # away inside the accumulate; strictness survives the f32 cast
    # because each increment (4·eps·scale) exceeds f32 ulp spacing.
    eps = np.finfo(np.float32).eps
    e = edges.astype(np.float64)
    d = 4.0 * eps * np.maximum(np.abs(e), 1.0)
    c = np.cumsum(d, axis=1) - d  # exclusive prefix sum
    return (c + np.maximum.accumulate(e - c, axis=1)).astype(np.float32)


def apply_bins(x, edges):
    """x [N, F] float → bin ids [N, F] int32 via per-feature searchsorted."""
    x = jnp.asarray(x, dtype=jnp.float32)
    edges = jnp.asarray(edges, dtype=jnp.float32)
    binned = jax.vmap(
        lambda col, cuts: jnp.searchsorted(cuts, col, side="left"),
        in_axes=(1, 0), out_axes=1,
    )(x, edges)
    return binned.astype(jnp.int32)


def _apply_bins_np(x: np.ndarray, edges: np.ndarray,
                   num_bins: int) -> np.ndarray:
    """Host-side twin of :func:`apply_bins` in the smallest dtype that
    holds the ids — for streaming/multi-process paths where the binned
    matrix is assembled on the host anyway (a device round trip would
    D2H the matrix right back)."""
    dt = (np.uint8 if num_bins <= 256
          else np.uint16 if num_bins <= 65536 else np.int32)
    out = np.empty(x.shape, dtype=dt)
    for f in range(x.shape[1]):
        out[:, f] = np.searchsorted(edges[f], x[:, f], side="left")
    return out


# ---------------------------------------------------------------------------
# gradients
# ---------------------------------------------------------------------------


def _grad_hess(objective: str, margin, label):
    """Per-row (g, h) for the second-order boosting objective.

    softmax: margin is [N, K], label holds class ids; (g, h) are [N, K]
    with the diagonal-hessian approximation p(1−p) — xgboost's
    multi:softprob formulation (K channels share one tree structure)."""
    if objective == "logistic":
        p = jax.nn.sigmoid(margin)
        return p - label, jnp.maximum(p * (1.0 - p), 1e-16)
    if objective == "squared":
        return margin - label, jnp.ones_like(margin)
    if objective == "softmax":
        p = jax.nn.softmax(margin, axis=-1)
        onehot = jax.nn.one_hot(
            label.astype(jnp.int32), margin.shape[-1], dtype=margin.dtype
        )
        return p - onehot, jnp.maximum(p * (1.0 - p), 1e-16)
    raise ValueError(f"unknown objective {objective!r}")


def _loss(objective: str, margin, label):
    if objective == "logistic":
        return jnp.maximum(margin, 0.0) - margin * label + jnp.log1p(
            jnp.exp(-jnp.abs(margin))
        )
    if objective == "softmax":
        logp = jax.nn.log_softmax(margin, axis=-1)
        return -jnp.take_along_axis(
            logp, label.astype(jnp.int32)[:, None], axis=1
        )[:, 0]
    return 0.5 * (margin - label) ** 2


def _grad_loss_core(objective: str, margin, y, w, psum_axis):
    """(g, h, weighted mean loss) for one boosting round — the ONE
    definition both the fused-scan and per-tree-loop paths trace (like
    _build_tree_core: a change here cannot diverge the two paths'
    models). Instance weights scale (g, h) — xgboost's semantics: a
    weight-2 row contributes exactly like two copies of itself to every
    histogram, split gain, and leaf value."""
    g, h = _grad_hess(objective, margin, y)
    if w is not None:
        wexp = w if g.ndim == 1 else w[:, None]
        g = g * wexp
        h = h * wexp
        lsum = jnp.sum(w * _loss(objective, margin, y))
        wsum = jnp.sum(w)
        if psum_axis is not None:
            lsum, wsum = jax.lax.psum((lsum, wsum), psum_axis)
        return g, h, lsum / jnp.maximum(wsum, 1e-12)
    loss = jnp.mean(_loss(objective, margin, y))
    if psum_axis is not None:
        loss = jax.lax.pmean(loss, psum_axis)
    return g, h, loss


def _margin_update_core(margin, leaf, node, learning_rate):
    # leaf [2^D] (scalar objectives) or [2^D, K] (softmax): axis-0 take
    # serves both, yielding [N] or [N, K] updates
    return margin + learning_rate * jnp.take(leaf, node, axis=0)


# ---------------------------------------------------------------------------
# one tree, level by level (all static shapes)
# ---------------------------------------------------------------------------


def _level_histogram(xb, node, g, h, n_nodes, num_bins):
    """(grad, hess) histogram [n_nodes, F, num_bins, C] by segment-sum.

    One flat key (node, feature, bin) per (sample, feature) cell; a
    single scatter pass fills all 2C channels (C = 1 for scalar
    objectives, K for softmax — the channels share one key, so
    multiclass costs one wider scatter, not K scatters). Every sample
    stays live through the build (leaf-in-place nodes route left), so no
    masking pass is needed.
    """
    nf = xb.shape[1]
    n_seg = n_nodes * nf * num_bins
    # the key space can exceed int32 at permitted hyperparameters (e.g.
    # num_bins=65536, F=1024, depth≥6), where the flat key would wrap
    # negative and segment_sum silently misroutes updates. An int64
    # fallback is NOT a fix: jax defaults to x64-disabled, so the cast
    # would quietly truncate back to int32. Refuse loudly instead.
    check(
        n_seg < (1 << 31),
        "histogram key space nodes*features*bins = %d*%d*%d = %d overflows "
        "int32; reduce max_depth, num_bins, or the feature count "
        "(or shard features) so the product stays below 2**31",
        n_nodes, nf, num_bins, n_seg,
    )
    key_dtype = jnp.int32
    feat = jnp.arange(nf, dtype=key_dtype)[None, :]
    flat = (
        (node[:, None].astype(key_dtype) * nf + feat) * num_bins
        + xb.astype(key_dtype)
    ).reshape(-1)
    g2 = g[:, None] if g.ndim == 1 else g
    h2 = h[:, None] if h.ndim == 1 else h
    c = g2.shape[1]
    gh = jnp.concatenate([g2, h2], axis=1)  # [N, 2C]
    vals = jnp.broadcast_to(
        gh[:, None, :], (gh.shape[0], nf, 2 * c)
    ).reshape(-1, 2 * c)
    hist = jax.ops.segment_sum(vals, flat, num_segments=n_seg)
    hist = hist.reshape(n_nodes, nf, num_bins, 2 * c)
    return hist[..., :c], hist[..., c:]


def _find_splits(ghist, hhist, reg_lambda, min_child_weight,
                 feat_mask=None):
    """Vectorized best split per node.

    ghist/hhist [n_nodes, F, B, C] → (feature [n_nodes], bin [n_nodes],
    gain [n_nodes], gtot [n_nodes, C], htot [n_nodes, C]). A split at
    bin t sends bins ≤ t left. gain = ½ Σ_c (GL²/(HL+λ) + GR²/(HR+λ) −
    G²/(H+λ)), the xgboost structure score summed over channels (all
    classes share one structure); children whose total hessian is under
    min_child_weight are masked out. feature = -1 flags "no
    positive-gain split" (leaf).
    """
    gl = jnp.cumsum(ghist, axis=2)
    hl = jnp.cumsum(hhist, axis=2)
    gtot = gl[:, 0, -1]  # [n, C] (identical for every feature)
    htot = hl[:, 0, -1]
    gr = gtot[:, None, None, :] - gl
    hr = htot[:, None, None, :] - hl
    lam = reg_lambda

    def score(gsum, hsum):
        # an empty child at reg_lambda=0 is 0/0: select 0 instead of
        # letting a NaN survive the mask and poison every argmax
        denom = hsum + lam
        return jnp.where(
            denom > 0.0, gsum * gsum / denom, 0.0
        ).sum(axis=-1)

    gain = 0.5 * (
        score(gl, hl) + score(gr, hr)
        - score(gtot, htot)[:, None, None]
    )
    # cover = total hessian mass across channels (xgboost's multiclass
    # min_child_weight semantics)
    hl_tot = hl.sum(axis=-1)
    hr_tot = hr.sum(axis=-1)
    ok = (hl_tot >= min_child_weight) & (hr_tot >= min_child_weight)
    # the last bin's "split" sends everything left — never a real split
    ok = ok.at[:, :, -1].set(False)
    if feat_mask is not None:  # colsample: undrawn features can't split
        ok = ok & feat_mask[None, :, None]
    gain = jnp.where(ok, gain, -jnp.inf)
    flat = gain.reshape(gain.shape[0], -1)
    best = jnp.argmax(flat, axis=1)
    nbins = ghist.shape[2]
    feature = (best // nbins).astype(jnp.int32)
    split_bin = (best % nbins).astype(jnp.int32)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    feature = jnp.where(best_gain > 0.0, feature, -1)
    return feature, split_bin, best_gain, gtot, htot


def _stochastic_masks(base_key, tree_idx, n_rows, n_features, subsample,
                      colsample, psum_axis):
    """(row_mask [N] f32 | None, feat_mask [F] bool | None) for one tree.

    Deterministic per (seed, tree): ``fold_in(key, t)`` — so the fused
    scan and the live-logging loop produce IDENTICAL masks (proven by
    test). The feature draw uses the pre-axis key (every shard must mask
    the same ceil(c·F) features or their histograms disagree); the row
    draw folds in the shard index so different shards drop different
    rows — the distributed-bagging shape. Mesh builds therefore match
    single-device builds only at subsample=1 (stochastic distributed
    boosting differs by construction, as in xgboost).
    """
    k = jax.random.fold_in(base_key, tree_idx)
    feat_mask = None
    if colsample < 1.0:
        keep = max(1, int(np.ceil(colsample * n_features)))
        order = jax.random.permutation(jax.random.fold_in(k, 1),
                                       n_features)
        feat_mask = jnp.zeros((n_features,), dtype=bool).at[
            order[:keep]].set(True)
    row_mask = None
    if subsample < 1.0:
        rk = jax.random.fold_in(k, 2)
        if psum_axis is not None:
            rk = jax.random.fold_in(rk, jax.lax.axis_index(psum_axis))
        row_mask = (jax.random.uniform(rk, (n_rows,))
                    < subsample).astype(jnp.float32)
    return row_mask, feat_mask


def _apply_stochastic_masks(base_key, t, n_features, g, h, subsample,
                            colsample, psum_axis):
    """(masked g, masked h, feat_mask) for tree ``t`` — the ONE
    application of :func:`_stochastic_masks` both the scan body and the
    live-logging loop trace (bit-identical masks are what the
    scan==loop forest-equivalence test enforces)."""
    row_mask, feat_mask = _stochastic_masks(
        base_key, t, g.shape[0], n_features, subsample, colsample,
        psum_axis,
    )
    if row_mask is not None:
        rexp = row_mask if g.ndim == 1 else row_mask[:, None]
        g = g * rexp
        h = h * rexp
    return g, h, feat_mask


def _build_tree_core(xb, g, h, max_depth, num_bins, reg_lambda,
                     min_child_weight, psum_axis=None, feat_mask=None):
    """One tree, level by level, all static shapes; traceable inside jit,
    shard_map, AND lax.scan (no Python-level data dependence).

    Tree encoding (complete binary tree, n_internal = 2^D − 1 internal
    nodes then 2^D leaves): ``feature``/``bin`` [n_internal] (−1 = the
    node is a leaf-in-place: descent keeps every sample left so the
    subtree collapses to its leftmost leaf), ``leaf`` [2^D] f32 leaf
    values (−G/(H+λ), already learning-rate-free).

    With ``psum_axis``: xb/g/h are per-shard local; each level does local
    segment-sums and ONE psum of the stacked (g, h) histogram — the rabit
    allreduce. Everything after the psum is shard-invariant.
    """
    n_leaves = 1 << max_depth
    n = xb.shape[0]
    node = jnp.zeros((n,), dtype=jnp.int32)  # id within current level
    feats, bins, gains = [], [], []
    for depth in range(max_depth):
        n_nodes = 1 << depth
        ghist, hhist = _level_histogram(xb, node, g, h, n_nodes, num_bins)
        if psum_axis is not None:
            ghist, hhist = jax.lax.psum((ghist, hhist),
                                        axis_name=psum_axis)
        feature, split_bin, gain, _gt, _ht = _find_splits(
            ghist, hhist, reg_lambda, min_child_weight,
            feat_mask=feat_mask,
        )
        feats.append(feature)
        bins.append(split_bin)
        # realized gain per node (0 at leaf-in-place nodes): the raw
        # material of gain-based feature importance
        gains.append(jnp.where(feature >= 0, gain, 0.0))
        # descend: right iff this sample's bin at the split feature
        # exceeds the threshold; leaf-in-place nodes send all left
        nfeat = jnp.take(feature, node)  # [N]
        nbin = jnp.take(split_bin, node)
        fval = jnp.take_along_axis(
            xb, jnp.maximum(nfeat, 0)[:, None], axis=1
        )[:, 0]
        go_right = (nfeat >= 0) & (fval > nbin)
        node = node * 2 + go_right.astype(jnp.int32)
    # leaf values from the last level's (G, H) per leaf — [2^D] for
    # scalar objectives, [2^D, K] vector leaves for softmax
    gleaf = jax.ops.segment_sum(g, node, num_segments=n_leaves)
    hleaf = jax.ops.segment_sum(h, node, num_segments=n_leaves)
    if psum_axis is not None:
        gleaf, hleaf = jax.lax.psum((gleaf, hleaf), axis_name=psum_axis)
    # empty leaves at reg_lambda=0 are 0/0: emit 0 — unseen data can
    # route there at predict time and must not read NaN
    denom = hleaf + reg_lambda
    leaf = jnp.where(denom > 0.0, -gleaf / denom, 0.0)
    return (
        jnp.concatenate(feats),
        jnp.concatenate(bins),
        jnp.concatenate(gains),
        leaf,
        node,
    )


def make_tree_builder(
    max_depth: int,
    num_bins: int,
    reg_lambda: float,
    min_child_weight: float,
    mesh: Optional[Mesh] = None,
    axis: str = "dp",
    with_feat_mask: bool = False,
):
    """Jitted (xb, g, h[, feat_mask]) → tree arrays; the level loop is
    unrolled (depth is a compile-time constant, ≤ 12), so one jit covers
    the whole build. See :func:`_build_tree_core` for the encoding and
    mesh semantics; ``with_feat_mask`` adds the colsample feature mask
    as a trailing (replicated) argument."""

    def _build(xb, g, h, *maybe_mask):
        return _build_tree_core(
            xb, g, h, max_depth, num_bins, reg_lambda, min_child_weight,
            psum_axis=axis if mesh is not None else None,
            feat_mask=maybe_mask[0] if with_feat_mask else None,
        )

    if mesh is None:
        return instrumented_jit(_build, "gbdt.build_tree")
    data_specs = (P(axis), P(axis), P(axis)) + (
        (P(),) if with_feat_mask else ())
    sharded = shard_map(
        _build,
        mesh=mesh,
        in_specs=data_specs,
        out_specs=(P(), P(), P(), P(), P(axis)),
    )
    return instrumented_jit(sharded, "gbdt.build_tree")


def make_forest_builder(
    num_trees: int,
    max_depth: int,
    num_bins: int,
    reg_lambda: float,
    min_child_weight: float,
    learning_rate: float,
    objective: str,
    mesh: Optional[Mesh] = None,
    axis: str = "dp",
    weighted: bool = False,
    num_class: int = 0,
    with_eval: bool = False,
    subsample: float = 1.0,
    colsample: float = 1.0,
    seed: int = 0,
):
    """The whole boosting loop as ONE jitted ``lax.scan`` over trees.

    Per-tree Python loops pay (grad + build + margin-update) dispatches
    per tree — dozens of host→device round trips per fit, the dominant
    cost in dispatch-latency-bound settings (a tunneled chip most of all,
    but real dispatch overhead everywhere). Trees have identical static
    shapes, which is exactly the shape contract ``lax.scan`` wants: the
    carry is the margin, each step emits (feature, bin, leaf, loss), and
    the stacked ys ARE the ``{feature: [T, ...], ...}`` layout
    ``predict_trees`` consumes. One dispatch per fit; XLA sees the whole
    forest and schedules/fuses across the per-tree stages.

    Returns jitted ``(xb, y[, w][, xe, ye]) → (trees_dict, history [T]
    [, eval_history [T]])`` — the instance-weight array only when
    ``weighted``; the binned eval set (+ per-tree post-update eval
    losses in the output, the xgboost watchlist) only when
    ``with_eval`` (mesh builds don't take an eval set — evaluate the
    replicated model after fit instead).
    """
    psum_axis = axis if mesh is not None else None
    offsets = jnp.asarray(_tree_level_offsets(max_depth), dtype=jnp.int32)

    def _forest(xb, y, *rest):
        i = 0
        w = rest[i] if weighted else None
        i += 1 if weighted else 0
        xe, ye = (rest[i], rest[i + 1]) if with_eval else (None, None)

        def _zero_margin(ref):
            m = jnp.zeros_like(ref)
            if objective == "softmax":
                m = m[:, None] * jnp.ones((num_class,), dtype=jnp.float32)
            return m

        stochastic = subsample < 1.0 or colsample < 1.0
        base_key = jax.random.PRNGKey(seed)

        def body(carry, t):
            margin, vmargin = carry
            g, h, loss = _grad_loss_core(objective, margin, y, w,
                                         psum_axis)
            feat_mask = None
            if stochastic:
                g, h, feat_mask = _apply_stochastic_masks(
                    base_key, t, xb.shape[1], g, h, subsample,
                    colsample, psum_axis,
                )
            feature, split_bin, gain, leaf, node = _build_tree_core(
                xb, g, h, max_depth, num_bins, reg_lambda,
                min_child_weight, psum_axis, feat_mask=feat_mask,
            )
            margin = _margin_update_core(margin, leaf, node, learning_rate)
            if with_eval:
                vnode = _descend_tree(xe, feature, split_bin, max_depth,
                                      offsets)
                vmargin = _margin_update_core(vmargin, leaf, vnode,
                                              learning_rate)
                # post-update loss: "how good is the forest so far on
                # held-out data" — the watchlist quantity
                vloss = jnp.mean(_loss(objective, vmargin, ye))
            else:
                vloss = loss  # unused; keeps the scan ys uniform
            return (margin, vmargin), (
                feature, split_bin, gain, leaf, loss, vloss)

        # derive the initial margin FROM y (not fresh zeros): inside
        # shard_map the scan carry must match the body output's varying
        # manual axes, and only values computed from the sharded operand
        # carry that type
        vmargin0 = _zero_margin(ye) if with_eval else jnp.zeros(())
        _, (feats, bins, gains, leaves, losses, vlosses) = jax.lax.scan(
            body, (_zero_margin(y), vmargin0),
            jnp.arange(num_trees, dtype=jnp.int32)
        )
        trees = {"feature": feats, "bin": bins, "gain": gains,
                 "leaf": leaves}
        if with_eval:
            return trees, losses, vlosses
        return trees, losses

    if mesh is None:
        return instrumented_jit(_forest, "gbdt.forest")
    check(not with_eval,
          "mesh forest builds don't take an eval set — evaluate the "
          "replicated model after fit")
    data_specs = (P(axis), P(axis)) + ((P(axis),) if weighted else ())
    sharded = shard_map(
        _forest,
        mesh=mesh,
        in_specs=data_specs,
        out_specs=(P(), P()),
    )
    return instrumented_jit(sharded, "gbdt.forest")


def _tree_level_offsets(max_depth: int) -> np.ndarray:
    """Start offset of each level's nodes in the flat feature/bin arrays."""
    return np.cumsum([0] + [1 << d for d in range(max_depth)])[:-1]


def _descend_tree(xb, feature, split_bin, max_depth, offsets):
    """Leaf index [N] for binned rows under one tree's flat arrays —
    the D-gather descent shared by prediction and eval-set tracking."""
    node = jnp.zeros((xb.shape[0],), dtype=jnp.int32)
    for depth in range(max_depth):
        idx = offsets[depth] + node
        nfeat = jnp.take(feature, idx)
        nbin = jnp.take(split_bin, idx)
        fval = jnp.take_along_axis(
            xb, jnp.maximum(nfeat, 0)[:, None], axis=1
        )[:, 0]
        go_right = (nfeat >= 0) & (fval > nbin)
        node = node * 2 + go_right.astype(jnp.int32)
    return node


def predict_trees(trees: Dict, xb, max_depth: int):
    """Sum of leaf values over all trees for binned rows xb [N, F].

    trees: {"feature": [T, n_internal], "bin": [T, n_internal],
    "leaf": [T, 2^D] or [T, 2^D, K] (softmax vector leaves)} stacked
    over trees; the descent is D gathers per tree, vmapped over T — no
    data-dependent control flow. Returns [N] or [N, K].
    """
    offsets = jnp.asarray(_tree_level_offsets(max_depth), dtype=jnp.int32)

    def one_tree(feature, split_bin, leaf):
        node = _descend_tree(xb, feature, split_bin, max_depth, offsets)
        return jnp.take(leaf, node, axis=0)

    per_tree = jax.vmap(one_tree)(
        trees["feature"], trees["bin"], trees["leaf"]
    )  # [T, N] or [T, N, K]
    return jnp.sum(per_tree, axis=0)


class GBDTLearner:
    """In-core histogram boosting: fit(x, y) → trees (xgboost hist mode).

    With a ``mesh``, samples are sharded over ``axis`` for the histogram
    build (the distributed-xgboost layout: each worker holds a row shard,
    histograms allreduce) and the model is replicated. The margin cache is
    updated incrementally per tree — predictions never rescan the forest
    during training.
    """

    def __init__(self, mesh: Optional[Mesh] = None, axis: str = "dp",
                 **hyper):
        self.param = GBDTParam()
        self.param.init(hyper)
        self.mesh = mesh
        self.axis = axis
        self.edges: Optional[np.ndarray] = None
        self.trees: Optional[Dict] = None
        self._builder = None
        self._forest = None  # fused lax.scan boosting loop (default path)
        self._engine = None  # multi-process row-count sync, lazy
        self._eval_step = None  # cached watchlist step (loop path)
        self.eval_history: Optional[list] = None  # per-tree eval_set loss
        self.best_iteration: Optional[int] = None  # its argmin (0-based)

    # ---- fit -----------------------------------------------------------
    def _local_shards(self) -> int:
        """Shard sections THIS process's rows divide over along the axis
        (one shared implementation: ``parallel.local_axis_shards``)."""
        from dmlc_tpu.parallel import local_axis_shards

        return local_axis_shards(self.mesh, self.axis)

    def _check_divisible(self, n: int) -> None:
        if self.mesh is None:
            return
        shards = self._local_shards()
        check(n % shards == 0,
              "N %d (this process's rows) must divide its %d mesh shards "
              "(pad or trim the training set)", n, shards)

    def _get_engine(self):
        """Cached DeviceEngine for tiny cross-process agreement
        collectives (row counts, weighted-ness) — cached so its jitted
        reduction survives across fits."""
        if self._engine is None:
            from dmlc_tpu.collective.device import DeviceEngine

            self._engine = DeviceEngine(self.mesh)
        return self._engine

    def _check_edges(self, num_features: int) -> None:
        """User-supplied edges must match (F, num_bins-1): oversize bin
        ids would walk off the end of the segment key space and
        segment_sum SILENTLY drops out-of-range updates — wrong splits
        with no error (the failure mode this check converts into one)."""
        want = (num_features, self.param.num_bins - 1)
        check(self.edges.shape == want,
              "edges shape %s does not match (num_features, num_bins-1) "
              "= %s", self.edges.shape, want)

    def _sync_row_count(self, n_local: int, trim: bool) -> int:
        """Multi-process row-count agreement: ``make_array_from_process_
        local_data`` infers the global shape ASSUMING every process
        contributes equally — ragged counts produce divergent global
        shapes across processes and the level-psum hangs or crashes
        instead of erroring. One tiny allreduce makes ragged input either
        a clean trim (``trim=True``: everyone cuts to the global-min
        multiple of their shards) or a clean error."""
        if self.mesh is None or jax.process_count() <= 1:
            return n_local
        shards = self._local_shards()
        usable = (n_local // shards) * shards if trim else n_local
        # one allreduce carries both bounds: min(x) and min(-x) = -max(x)
        lo, neg_hi = (int(v) for v in self._get_engine().allreduce(
            np.array([usable, -usable]), op="min"))
        if trim:
            return lo
        check(lo == -neg_hi,
              "processes hold unequal row counts (%d..%d); global "
              "assembly requires equal local N — trim (fit_uri: "
              "drop_remainder=True) or pad", lo, -neg_hi)
        return n_local

    def fit(self, x: np.ndarray, y: np.ndarray, log_every: int = 0,
            edges: Optional[np.ndarray] = None,
            weight: Optional[np.ndarray] = None,
            eval_set: Optional[tuple] = None):
        """Train on an in-memory dense [N, F] float matrix. Returns the
        per-tree weighted mean loss history (evaluated pre-update, so
        entry 0 is the base-margin loss).

        ``weight`` [N] scales each row's (g, h) — xgboost's instance
        weights: a weight-2 row trains exactly like two copies of it
        (histograms, split gains, leaf values; proven by test).

        ``eval_set=(x_val, y_val)`` tracks the held-out loss after every
        tree (the xgboost watchlist) INSIDE the fused scan — no extra
        dispatches; afterwards ``self.eval_history`` holds the per-tree
        losses and ``self.best_iteration`` the argmin, which
        :meth:`truncate` can cut the forest back to. Single-process only
        (evaluate a replicated mesh model after fit instead).

        Multi-process meshes: ``x``/``y`` are this process's LOCAL rows,
        and every process must pass IDENTICAL ``edges`` (bin boundaries
        are the one piece of global state the histogram psum assumes —
        the reference stack's analog is rabit allreducing xgboost's
        quantile sketches; compute them from a shared sample, or on rank
        0 and broadcast via the collective engine).

        With a mesh AND subsample/colsample_bytree < 1, ``log_every>0``
        trains a DIFFERENT (equally valid) forest than the default fused
        scan: the scan's shard_map folds the shard index into the mask
        PRNG, which the live-logging path's plain jit cannot reproduce.
        A warning is emitted; use ``log_every=0`` when you need the
        scan-identical model.
        """
        p = self.param
        x = np.asarray(x, dtype=np.float32)
        y = np.asarray(y, dtype=np.float32)
        check(x.ndim == 2 and y.shape == (x.shape[0],),
              "fit expects x [N, F], y [N]")
        if weight is not None:
            weight = np.asarray(weight, dtype=np.float32)
            check(weight.shape == y.shape, "weight must be [N]")
        if eval_set is not None:
            check(self.mesh is None,
                  "eval_set requires mesh=None (evaluate the replicated "
                  "model after a mesh fit)")
            xe = np.asarray(eval_set[0], dtype=np.float32)
            ye = np.asarray(eval_set[1], dtype=np.float32)
            check(xe.ndim == 2 and xe.shape[1] == x.shape[1]
                  and ye.shape == (xe.shape[0],),
                  "eval_set must be (x_val [Ne, F], y_val [Ne])")
        multiprocess = self.mesh is not None and jax.process_count() > 1
        if multiprocess:
            check(edges is not None,
                  "multi-process fit requires shared edges= (per-host "
                  "quantiles would bin the same value differently)")
            self._sync_row_count(x.shape[0], trim=False)
        self._check_divisible(x.shape[0])
        if not multiprocess and jax.default_backend() != "cpu":
            # ONE H2D of the float matrix feeds both the device quantile
            # (fit_bins accelerator path) and the device searchsorted
            x = jnp.asarray(x)
        if edges is not None:
            self.edges = np.asarray(edges, dtype=np.float32)
            self._check_edges(x.shape[1])
        else:
            self.edges = fit_bins(x, p.num_bins)
        if multiprocess:
            # bin on host: the global assembly consumes host arrays, so
            # device apply_bins would D2H the matrix straight back
            return self._fit_binned(
                _apply_bins_np(x, self.edges, p.num_bins), y, log_every,
                weight)
        # apply_bins already lives on device; _fit_binned's jnp.asarray
        # is a no-op there (a np.asarray round trip would D2H+H2D the
        # whole matrix for nothing)
        eval_xb = eval_y = None
        if eval_set is not None:
            eval_xb = apply_bins(xe, self.edges)
            eval_y = ye
        return self._fit_binned(apply_bins(x, self.edges), y, log_every,
                                weight, eval_xb, eval_y)

    def fit_uri(
        self,
        uri: str,
        num_features: int,
        part_index: int = 0,
        num_parts: int = 1,
        sample_rows: int = 1 << 16,
        log_every: int = 0,
        drop_remainder: bool = False,
        edges: Optional[np.ndarray] = None,
        nthread: Optional[int] = None,
    ):
        """Train from any parser uri (LibSVM text, RecordIO row groups,
        ``#cachefile``, object store) without materializing the dense
        float matrix — the external-memory answer for hist mode:

        pass 1 streams blocks through a vectorized reservoir sample
        (Algorithm R) to fit the bin edges (``sample_rows`` caps the
        sketch; ≥ N keeps every row and reproduces ``fit`` exactly);
        pass 2 re-streams (``before_first``) and bins each block on the
        host into the compact binned matrix (uint8/uint16 when num_bins
        allows — ~4-8x smaller than the float matrix it replaces).

        Multi-host: pass the per-host InputSplit part via
        part_index/num_parts (the reference's part-k/n sharding contract).
        Binary row-group shards ride the same call via the reference's
        own format idiom (src/data.cc:70-76): ``uri + "?format=recordio"``.
        Under a mesh, ``drop_remainder=True`` trims the tail rows that
        don't divide the axis extent (a uri's row count is unknown up
        front); the default raises instead of silently dropping data.
        Multi-process: each process parses its own part AND must receive
        identical ``edges=`` (see ``fit``) — passing them also skips the
        sketch pass entirely. ``nthread`` fans chunk parsing across
        worker threads (None → the ``DMLC_TPU_NTHREAD`` env knob).
        """
        from dmlc_tpu.data import create_parser

        p = self.param
        check(num_features > 0, "fit_uri requires num_features")
        if self.mesh is not None and jax.process_count() > 1:
            check(edges is not None,
                  "multi-process fit_uri requires shared edges= (per-host "
                  "sketches would bin the same value differently)")
        parser = create_parser(uri, part_index, num_parts, nthread=nthread)
        try:
            if edges is not None:
                self.edges = np.asarray(edges, dtype=np.float32)
                self._check_edges(num_features)
            else:
                # pass 1: reservoir sample for edges
                rng = np.random.RandomState(p.num_bins * 7919 + 13)
                reservoir = np.empty((sample_rows, num_features),
                                     dtype=np.float32)
                seen = 0
                for block in parser:
                    dense = block.to_dense(num_features)
                    n = len(dense)
                    gidx = np.arange(seen, seen + n)
                    take_direct = gidx < sample_rows
                    reservoir[gidx[take_direct]] = dense[take_direct]
                    rest = ~take_direct
                    if rest.any():
                        draws = (rng.random_sample(int(rest.sum()))
                                 * (gidx[rest] + 1)).astype(np.int64)
                        hit = draws < sample_rows
                        reservoir[draws[hit]] = dense[rest][hit]
                    seen += n
                check(seen > 0, "uri produced no rows: %s", uri)
                self.edges = fit_bins(reservoir[:min(seen, sample_rows)],
                                      p.num_bins)
            # pass 2: stream + bin on the host (no device chatter per
            # block)
            from dmlc_tpu import obs

            parser.before_first()
            xb_parts, y_parts, w_parts = [], [], []
            any_weight = False
            for block in parser:
                # gbdt consumes chunks here (no DeviceFeed): the binning
                # slice terminates each pipelined chunk's arrow chain
                fid = getattr(block, "flow_id", 0)
                with obs.span("bin_block", rows=len(block), flow=fid):
                    obs.flow_end(fid, "chunk")
                    dense = block.to_dense(num_features)
                    xb_parts.append(
                        _apply_bins_np(dense, self.edges, p.num_bins))
                y_parts.append(np.asarray(block.label, dtype=np.float32))
                # instance weights ride the format when present (libsvm
                # label:weight — data.h Row semantics); all-absent stays
                # the unweighted fast path
                if block.weight is not None:
                    any_weight = True
                    w_parts.append(
                        np.asarray(block.weight, dtype=np.float32))
                else:
                    w_parts.append(
                        np.ones(len(block), dtype=np.float32))
        finally:
            parser.close()
        # both branches must fail cleanly on a rowless uri/part (a
        # byte-split part of a small file can legitimately be empty; on a
        # mesh, dying in np.concatenate would strand the other processes
        # in the row-count collective)
        check(xb_parts, "uri produced no rows: %s (part %d/%d)",
              uri, part_index, num_parts)
        # keep the compact dtype — _level_histogram widens bin ids into
        # the (int32/int64) segment key itself, so upcasting here would
        # re-materialize the float-matrix-sized array fit_uri exists to
        # avoid
        xb = np.concatenate(xb_parts)
        y = np.concatenate(y_parts)
        if self.mesh is not None and jax.process_count() > 1:
            # weighted-ness must agree across the world: a process whose
            # part happens to carry no label:weight rows would otherwise
            # build the 2-input SPMD program while its peers build the
            # 3-input one — mismatched executables against the same
            # collectives. Any process's weights make the fit weighted
            # (the ones-fill above already covers the absent rows).
            any_weight = bool(self._get_engine().allreduce(
                np.array([int(any_weight)]), op="max")[0])
        weight = np.concatenate(w_parts) if any_weight else None
        if drop_remainder and self.mesh is not None:
            shards = self._local_shards()
            # equalize ACROSS processes too: global assembly assumes every
            # process contributes the same local N (ragged InputSplit
            # parts are the norm, not the exception)
            n = self._sync_row_count((xb.shape[0] // shards) * shards,
                                     trim=True)
            xb, y = xb[:n], y[:n]
            if weight is not None:
                weight = weight[:n]
        else:
            self._sync_row_count(xb.shape[0], trim=False)
        self._check_divisible(xb.shape[0])
        return self._fit_binned(xb, y, log_every, weight)

    def _fit_binned(self, xb: np.ndarray, y: np.ndarray, log_every: int,
                    weight: Optional[np.ndarray] = None,
                    eval_xb=None, eval_y=None):
        from dmlc_tpu import obs
        from dmlc_tpu.utils.logging import log_info

        p = self.param
        # one fit = one "epoch"; trees are the steps (both the fused-scan
        # and the live-logging path funnel their history through _obs_fit,
        # and both go through the shared fit-loop helper — same metrics,
        # goodput window, and watchdog pass as the feed-driven learners)
        from dmlc_tpu.models.fitloop import FitLoopObs

        fl = FitLoopObs("gbdt")
        _t_fit = time.monotonic_ns()

        def _obs_fit(history):
            fl.note_step(len(history))
            fl.end_epoch(0, len(history), _t_fit,
                         history[-1] if history else None)
            return history
        if p.objective == "softmax":
            # the shared chokepoint: fit AND fit_uri funnel here, so both
            # get the clean errors (out-of-range ids silently one_hot to
            # all-zero rows and train a NaN model otherwise)
            check(p.num_class >= 2,
                  "objective=softmax requires num_class >= 2")
            for arr, what in ((y, "softmax labels"),
                              (eval_y, "softmax eval labels")):
                if arr is None:
                    continue
                a = np.asarray(arr)
                check(len(a) == 0 or (
                    float(a.min()) >= 0 and float(a.max()) < p.num_class),
                    "%s must be class ids in [0, %d)", what, p.num_class)
        weighted = weight is not None
        multiprocess = self.mesh is not None and jax.process_count() > 1
        if multiprocess:
            # each process contributes its local rows; the global array
            # spans the world (DeviceFeed._put_tree's multi-host shape)
            shard = NamedSharding(self.mesh, P(self.axis))
            y_np = np.asarray(y, dtype=np.float32)
            xb = jax.make_array_from_process_local_data(
                shard, np.asarray(xb))
            yd = jax.make_array_from_process_local_data(shard, y_np)
            if weighted:
                weight = jax.make_array_from_process_local_data(
                    shard, np.asarray(weight, dtype=np.float32))
        else:
            xb = jnp.asarray(xb)
            yd = jnp.asarray(y)
            if weighted:
                weight = jnp.asarray(weight, dtype=jnp.float32)
            if self.mesh is not None:
                shard = NamedSharding(self.mesh, P(self.axis))
                xb = jax.device_put(xb, shard)
                yd = jax.device_put(yd, shard)
                if weighted:
                    weight = jax.device_put(weight, shard)
        with_eval = eval_xb is not None
        if with_eval:
            eval_xb = jnp.asarray(eval_xb)
            eval_yd = jnp.asarray(eval_y)
        self.eval_history = None
        self.best_iteration = None
        wargs = (weight,) if weighted else ()
        eargs = (eval_xb, eval_yd) if with_eval else ()
        if not log_every:
            # the default path: the WHOLE boosting loop is one lax.scan
            # dispatch (make_forest_builder) — per-tree dispatch overhead
            # retired, XLA schedules across tree stages
            if self._forest is None or self._forest[0] != (weighted,
                                                           with_eval):
                self._forest = ((weighted, with_eval), make_forest_builder(
                    p.num_trees, p.max_depth, p.num_bins, p.reg_lambda,
                    p.min_child_weight, p.learning_rate, p.objective,
                    self.mesh, self.axis, weighted=weighted,
                    num_class=p.num_class, with_eval=with_eval,
                    subsample=p.subsample,
                    colsample=p.colsample_bytree, seed=p.seed,
                ))
            with obs.span("fit", model="gbdt", trees=p.num_trees):
                out = self._forest[1](xb, yd, *wargs, *eargs)
            if with_eval:
                self.trees, losses, vlosses = out
                self._set_eval_history(np.asarray(vlosses))
            else:
                self.trees, losses = out
            return _obs_fit([float(v) for v in np.asarray(losses)])
        # live-logging path: one dispatch per tree so losses stream out
        # while training runs (the scan only reports at the end). Only
        # this path carries a margin across dispatches.
        mshape = ((len(y),) if p.objective != "softmax"
                  else (len(y), p.num_class))
        if multiprocess:
            margin = jax.make_array_from_process_local_data(
                shard, np.zeros(mshape, dtype=np.float32))
        else:
            margin = jnp.zeros(mshape, dtype=jnp.float32)
        stochastic = p.subsample < 1.0 or p.colsample_bytree < 1.0
        colsample_on = p.colsample_bytree < 1.0
        if self._builder is None or self._builder[0] != colsample_on:
            self._builder = (colsample_on, make_tree_builder(
                p.max_depth, p.num_bins, p.reg_lambda,
                p.min_child_weight, self.mesh, self.axis,
                with_feat_mask=colsample_on,
            ))
        if stochastic:
            # jitted so the mask math runs with global-array semantics
            # (an eager multiply would reject multi-process sharded g/h).
            # Same helper + fold_in scheme as the scan body — identical
            # masks and therefore identical forests at mesh=None (the
            # mesh scan also folds in the shard index, which a
            # non-shard_map jit cannot: there the two paths are both
            # valid stochastic boosting but not mask-identical). The
            # closure constant is a 2-int key — no recompile concern.
            if self.mesh is not None:
                from dmlc_tpu.utils.logging import log_warning
                log_warning(
                    "gbdt: log_every with mesh + subsample/colsample < 1 "
                    "draws different stochastic masks than the fused-scan "
                    "path (log_every=0), so the two settings train "
                    "different (equally valid) forests; set log_every=0 "
                    "for a scan-identical model")
            base_key = jax.random.PRNGKey(p.seed)
            nf = int(xb.shape[1])
            mask_step = instrumented_jit(
                lambda t, g, h: _apply_stochastic_masks(
                    base_key, t, nf, g, h, p.subsample,
                    p.colsample_bytree, None),
                "gbdt.mask_step")
        grad_fn = self._make_grad_fn(weighted)
        update_fn = self._make_margin_update()
        if with_eval:
            eval_step = self._make_eval_step()
            vshape = ((len(eval_y),) if p.objective != "softmax"
                      else (len(eval_y), p.num_class))
            vmargin = jnp.zeros(vshape, dtype=jnp.float32)
            vlosses = []
        feats, bins, gains, leaves = [], [], [], []
        history = []
        with obs.span("fit", model="gbdt", trees=p.num_trees):
            for t in range(p.num_trees):
                g, h, mean_loss = grad_fn(margin, yd, *wargs)
                margs = ()
                if stochastic:
                    g, h, feat_mask = mask_step(t, g, h)
                    if colsample_on:
                        margs = (feat_mask,)
                feature, split_bin, gain, leaf, node = self._builder[1](
                    xb, g, h, *margs)
                feats.append(feature)
                bins.append(split_bin)
                gains.append(gain)
                leaves.append(leaf)
                margin = update_fn(margin, leaf, node)
                history.append(float(mean_loss))
                if with_eval:
                    vmargin, vloss = eval_step(eval_xb, eval_yd, feature,
                                               split_bin, leaf, vmargin)
                    vlosses.append(float(vloss))
                if (t + 1) % log_every == 0:
                    log_info("tree %d loss %.6f", t + 1, history[-1])
        self.trees = {
            "feature": jnp.stack(feats),
            "bin": jnp.stack(bins),
            "gain": jnp.stack(gains),
            "leaf": jnp.stack(leaves),
        }
        if with_eval:
            self._set_eval_history(np.asarray(vlosses))
        return _obs_fit(history)

    def _make_grad_fn(self, weighted: bool = False):
        objective = self.param.objective

        def _fn(margin, y, *maybe_w, axis=None):
            return _grad_loss_core(
                objective, margin, y,
                maybe_w[0] if weighted else None, axis)

        if self.mesh is None:
            return instrumented_jit(_fn, "gbdt.grad")
        data = (P(self.axis),) * (3 if weighted else 2)
        return instrumented_jit(shard_map(
            lambda *args: _fn(*args, axis=self.axis),
            mesh=self.mesh,
            in_specs=data,
            out_specs=(P(self.axis), P(self.axis), P()),
        ), "gbdt.grad")

    def _make_margin_update(self):
        lr = self.param.learning_rate

        def _fn(margin, leaf, node):
            return _margin_update_core(margin, leaf, node, lr)

        if self.mesh is None:
            return instrumented_jit(_fn, "gbdt.margin_update")
        return instrumented_jit(shard_map(
            _fn, mesh=self.mesh,
            in_specs=(P(self.axis), P(), P(self.axis)),
            out_specs=P(self.axis),
        ), "gbdt.margin_update")

    # ---- predict -------------------------------------------------------
    def predict_margin(self, x: np.ndarray) -> np.ndarray:
        check(self.trees is not None, "model not fitted")
        xb = apply_bins(np.asarray(x, dtype=np.float32), self.edges)
        margin = self.param.learning_rate * predict_trees(
            self.trees, xb, self.param.max_depth
        )
        return np.asarray(margin)

    def predict(self, x: np.ndarray) -> np.ndarray:
        """Probabilities under logistic ([N]) and softmax ([N, K],
        xgboost multi:softprob — argmax for class ids), raw margin under
        squared."""
        margin = self.predict_margin(x)
        if self.param.objective == "logistic":
            return np.asarray(jax.nn.sigmoid(jnp.asarray(margin)))
        if self.param.objective == "softmax":
            return np.asarray(jax.nn.softmax(jnp.asarray(margin), axis=-1))
        return margin

    # ---- checkpointing via the Stream surface (SURVEY §5.4) -------------
    def save(self, uri: str) -> None:
        from dmlc_tpu.io.filesystem import create_stream
        from dmlc_tpu.io.serializer import save_obj

        check(self.trees is not None, "model not fitted")
        with create_stream(uri, "w") as out:
            payload = {
                "param": self.param.to_dict(),
                "edges": np.asarray(self.edges),
                "feature": np.asarray(self.trees["feature"]),
                "bin": np.asarray(self.trees["bin"]),
                "leaf": np.asarray(self.trees["leaf"]),
            }
            if "gain" in self.trees:  # tolerant like load: a model
                # restored from a pre-gain checkpoint must stay savable
                payload["gain"] = np.asarray(self.trees["gain"])
            save_obj(out, payload)

    def load(self, uri: str) -> None:
        from dmlc_tpu.io.filesystem import create_stream
        from dmlc_tpu.io.serializer import load_obj

        with create_stream(uri, "r") as stream:
            payload = load_obj(stream)
        self.param.init(payload["param"], allow_unknown=True)
        # the cached builders bake in the PREVIOUS hyperparameters; a
        # fit() after load() must rebuild them against the restored ones
        self._builder = None
        self._forest = None
        self._eval_step = None
        self.edges = payload["edges"]
        self.trees = {
            "feature": jnp.asarray(payload["feature"]),
            "bin": jnp.asarray(payload["bin"]),
            "leaf": jnp.asarray(payload["leaf"]),
        }
        if "gain" in payload:  # absent in pre-gain checkpoints
            self.trees["gain"] = jnp.asarray(payload["gain"])

    def _make_eval_step(self):
        """Cached jitted watchlist step for the live-logging path: the
        eval arrays are ARGUMENTS, not closure constants (a fresh
        closure per fit would bake [Ne, F] into the jaxpr and recompile
        every call)."""
        if getattr(self, "_eval_step", None) is None:
            p = self.param
            offsets = jnp.asarray(_tree_level_offsets(p.max_depth),
                                  dtype=jnp.int32)
            lr = p.learning_rate
            objective = p.objective

            def eval_step(exb, eyd, feature, split_bin, leaf, vmargin):
                vnode = _descend_tree(exb, feature, split_bin,
                                      p.max_depth, offsets)
                vmargin = _margin_update_core(vmargin, leaf, vnode, lr)
                return vmargin, jnp.mean(_loss(objective, vmargin, eyd))

            self._eval_step = instrumented_jit(eval_step, "gbdt.eval_step")
        return self._eval_step

    def _set_eval_history(self, vlosses: np.ndarray) -> None:
        self.eval_history = [float(v) for v in vlosses]
        self.best_iteration = int(np.argmin(vlosses))

    def truncate(self, num_trees: int) -> None:
        """Cut the forest back to its first ``num_trees`` trees — the
        early-stopping companion to ``best_iteration`` (a scan has
        static length, so selection happens after the fit):

            learner.fit(x, y, eval_set=(xv, yv))
            learner.truncate(learner.best_iteration + 1)
        """
        check(self.trees is not None, "model not fitted")
        total = self.trees["feature"].shape[0]
        check(1 <= num_trees <= total,
              "num_trees must be in [1, %d]", total)
        self.trees = {k: v[:num_trees] for k, v in self.trees.items()}

    def feature_importance(self, kind: str = "gain") -> np.ndarray:
        """Per-feature importance [F] — xgboost get_score semantics:
        ``gain`` sums each feature's realized split gains over the
        forest; ``split`` counts its splits."""
        check(self.trees is not None, "model not fitted")
        check(kind in ("gain", "split"), "kind must be gain or split")
        feats = np.asarray(self.trees["feature"]).ravel()
        if kind == "split":
            vals = np.ones_like(feats, dtype=np.float32)
        else:
            check("gain" in self.trees,
                  "checkpoint predates gain recording — refit for "
                  "gain importance (split importance still works)")
            vals = np.asarray(self.trees["gain"]).ravel()
        mask = feats >= 0
        out = np.zeros(self.edges.shape[0], dtype=np.float32)
        np.add.at(out, feats[mask], vals[mask])
        return out
