"""Shared fit-loop observability: one epoch-boundary helper for every
learner.

Before this module each learner hand-rolled the same block — register
the four ``dmlc_fit_*`` metrics, observe the epoch histogram, log the
feed's stall breakdown (linear only, behind a function-local import),
export the registry. :class:`FitLoopObs` is that block once, plus the
runtime instruments this layer gained: a goodput ledger window per
epoch (obs/goodput.py) and the SLO watchdog over those windows
(obs/watchdog.py). linear, FM, and GBDT all funnel through it, so the
epoch log line and the binding-constraint verdict are uniform across
models.

Usage::

    fl = FitLoopObs("linear")
    for epoch in range(epochs):
        t0 = time.monotonic_ns()
        for batch in feed:
            ...
            fl.note_step()
        fl.end_epoch(epoch, nstep, t0, loss, feed=feed,
                     log_every=log_every)

It also owns the sampled device-step latency probe: every
``DMLC_TPU_STEP_SAMPLE_N``-th step the loop calls
:meth:`FitLoopObs.sample_latency` on the step's output, which times one
``jax.block_until_ready`` drain and records ``dmlc_step_device_ms`` —
the dispatch-to-drain latency of the compiled step (a
block-after-dispatch approximation of device step time; on an async
backend it includes whatever the dispatch queue still held). The other
N−1 steps pay one integer increment and no sync, pinned by test; with
device telemetry or metrics off the stride is 0 and the call is a bare
attribute read.

Under ``DMLC_TPU_METRICS=0`` the registry hands back no-op children and
the ledger/watchdog collapse to the shared no-op child, so the hot path
stays allocation-free.
"""

from __future__ import annotations

import time
from typing import Optional

from dmlc_tpu import obs
from dmlc_tpu.device.feed import stall_breakdown
from dmlc_tpu.obs import audit, goodput
from dmlc_tpu.obs.metrics import metrics_enabled
from dmlc_tpu.obs.watchdog import make_watchdog
from dmlc_tpu.params.knobs import device_telemetry_enabled, step_sample_n
from dmlc_tpu.utils.logging import log_info


class FitLoopObs:
    """Per-fit observability bundle: fit metrics, stall logging, the
    goodput ledger, and the runtime watchdog."""

    def __init__(self, model: str, reg=None):
        self.model = model
        self.reg = reg if reg is not None else obs.registry()
        self.m_steps = self.reg.counter(
            "dmlc_fit_steps_total", "optimizer steps taken", model=model)
        self.m_epochs = self.reg.counter(
            "dmlc_fit_epochs_total", "epochs completed", model=model)
        self.g_loss = self.reg.gauge(
            "dmlc_fit_loss_value", "last epoch mean loss", model=model)
        self.h_epoch = self.reg.histogram(
            "dmlc_fit_epoch_ns", "wall time per epoch", model=model)
        self.ledger = goodput.ledger(self.reg)
        self.watchdog = make_watchdog(self.reg)
        # determinism audit: the model digest chain + numeric sentinel
        # (the shared no-op child when DMLC_TPU_AUDIT is off)
        self.audit = audit.auditor()
        # device-step latency sampling stride: 0 (telemetry or metrics
        # off, or DMLC_TPU_STEP_SAMPLE_N=0) disarms sample_latency down
        # to one attribute read per step — read once, here, never per
        # dispatch
        self._sample_n = (
            step_sample_n()
            if device_telemetry_enabled() and metrics_enabled() else 0)
        self._sampled = 0
        self._h_step_ms = self.reg.histogram(
            "dmlc_step_device_ms",
            "sampled dispatch-to-drain latency of the optimizer step "
            "(block_until_ready on every DMLC_TPU_STEP_SAMPLE_N-th "
            "step's output)",
            model=model) if self._sample_n else None

    def note_step(self, n: int = 1) -> None:
        """Hot-path progress marker (one no-op call under
        ``DMLC_TPU_METRICS=0``)."""
        self.ledger.note_step(n)

    def sample_latency(self, out) -> None:
        """Sampled device-step latency: on every ``_sample_n``-th call,
        time one ``jax.block_until_ready(out)`` and record
        ``dmlc_step_device_ms``. Every other call is one increment and
        one modulo — no sync, no allocation (pinned by test); disarmed
        entirely (one attribute read) when the stride is 0."""
        n = self._sample_n
        if not n:
            return
        self._sampled += 1
        if self._sampled % n:
            return
        import jax

        t0 = time.monotonic_ns()
        jax.block_until_ready(out)
        self._h_step_ms.observe((time.monotonic_ns() - t0) / 1e6)

    def end_epoch(self, epoch: int, nstep: int, t0_ns: int,
                  loss: Optional[float], feed=None,
                  log_every: int = 0, params=None,
                  snapshotter=None, snap_state=None) -> Optional[dict]:
        """Close one epoch: fit metrics, a goodput-ledger window fed to
        the watchdog, the unified stall/goodput log line (every
        ``log_every``-th epoch), and the registry export. Returns the
        ledger window (None when metrics are disabled).

        ``params`` (optional dict of device arrays) extends the audit
        model-digest chain over a strided parameter sample — one small
        epoch-cadence fetch that doubles as the numeric-health sentinel
        (non-finite counts feed the watchdog's ``numeric`` alert).

        ``snapshotter`` + ``snap_state`` (a zero-arg state-tree builder)
        arm job snapshotting: after the audit roll, the boundary's state
        is host-captured and handed to the async writer
        (collective/snapshot.py) — capture after the roll so the
        exported audit state describes the *closed* epoch and a resume
        re-arms the chains exactly where an uninterrupted run would
        be."""
        self.h_epoch.observe(time.monotonic_ns() - t0_ns)
        self.m_steps.inc(nstep)
        self.m_epochs.inc()
        if loss is not None:
            self.g_loss.set(loss)
        nonfinite = self.audit.note_model(epoch, loss, params)
        win = self.ledger.tick()
        if win is not None:
            win["nonfinite"] = nonfinite
            self.watchdog.observe(win)
        if log_every and (epoch + 1) % log_every == 0:
            parts = ["%s epoch %d" % (self.model, epoch)]
            if loss is not None:
                parts.append("loss %.6f" % loss)
            if feed is not None:
                parts.append(stall_breakdown(feed.stats()))
            if win is not None:
                parts.append("goodput %.2f binding=%s" % (
                    win["goodput"]["ratio"], win["binding"]))
            log_info("%s", " ".join(parts))
        obs.export_epoch(self.reg)
        # roll AFTER the export/publish so the epoch's full data chains
        # rode the heartbeat; this also runs the epoch-over-epoch
        # self-check (first divergence writes the replay bundle)
        self.audit.roll_epoch(epoch)
        if snapshotter is not None and snap_state is not None:
            snapshotter.capture(epoch, snap_state)
        return win
