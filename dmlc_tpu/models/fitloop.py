"""Shared fit-loop observability: one epoch-boundary helper for every
learner.

Before this module each learner hand-rolled the same block — register
the four ``dmlc_fit_*`` metrics, observe the epoch histogram, log the
feed's stall breakdown (linear only, behind a function-local import),
export the registry. :class:`FitLoopObs` is that block once, plus the
runtime instruments this layer gained: a goodput ledger window per
epoch (obs/goodput.py) and the SLO watchdog over those windows
(obs/watchdog.py). linear, FM, and GBDT all funnel through it, so the
epoch log line and the binding-constraint verdict are uniform across
models.

Usage::

    fl = FitLoopObs("linear")
    for epoch in range(epochs):
        t0 = time.monotonic_ns()
        for batch in feed:
            ...
            fl.note_step()
        fl.end_epoch(epoch, nstep, t0, loss, feed=feed,
                     log_every=log_every)

Under ``DMLC_TPU_METRICS=0`` the registry hands back no-op children and
the ledger/watchdog collapse to the shared no-op child, so the hot path
stays allocation-free.
"""

from __future__ import annotations

import time
from typing import Optional

from dmlc_tpu import obs
from dmlc_tpu.device.feed import stall_breakdown
from dmlc_tpu.obs import audit, goodput
from dmlc_tpu.obs.watchdog import make_watchdog
from dmlc_tpu.utils.logging import log_info


class FitLoopObs:
    """Per-fit observability bundle: fit metrics, stall logging, the
    goodput ledger, and the runtime watchdog."""

    def __init__(self, model: str, reg=None):
        self.model = model
        self.reg = reg if reg is not None else obs.registry()
        self.m_steps = self.reg.counter(
            "dmlc_fit_steps_total", "optimizer steps taken", model=model)
        self.m_epochs = self.reg.counter(
            "dmlc_fit_epochs_total", "epochs completed", model=model)
        self.g_loss = self.reg.gauge(
            "dmlc_fit_loss_value", "last epoch mean loss", model=model)
        self.h_epoch = self.reg.histogram(
            "dmlc_fit_epoch_ns", "wall time per epoch", model=model)
        self.ledger = goodput.ledger(self.reg)
        self.watchdog = make_watchdog(self.reg)
        # determinism audit: the model digest chain + numeric sentinel
        # (the shared no-op child when DMLC_TPU_AUDIT is off)
        self.audit = audit.auditor()

    def note_step(self, n: int = 1) -> None:
        """Hot-path progress marker (one no-op call under
        ``DMLC_TPU_METRICS=0``)."""
        self.ledger.note_step(n)

    def end_epoch(self, epoch: int, nstep: int, t0_ns: int,
                  loss: Optional[float], feed=None,
                  log_every: int = 0, params=None,
                  snapshotter=None, snap_state=None) -> Optional[dict]:
        """Close one epoch: fit metrics, a goodput-ledger window fed to
        the watchdog, the unified stall/goodput log line (every
        ``log_every``-th epoch), and the registry export. Returns the
        ledger window (None when metrics are disabled).

        ``params`` (optional dict of device arrays) extends the audit
        model-digest chain over a strided parameter sample — one small
        epoch-cadence fetch that doubles as the numeric-health sentinel
        (non-finite counts feed the watchdog's ``numeric`` alert).

        ``snapshotter`` + ``snap_state`` (a zero-arg state-tree builder)
        arm job snapshotting: after the audit roll, the boundary's state
        is host-captured and handed to the async writer
        (collective/snapshot.py) — capture after the roll so the
        exported audit state describes the *closed* epoch and a resume
        re-arms the chains exactly where an uninterrupted run would
        be."""
        self.h_epoch.observe(time.monotonic_ns() - t0_ns)
        self.m_steps.inc(nstep)
        self.m_epochs.inc()
        if loss is not None:
            self.g_loss.set(loss)
        nonfinite = self.audit.note_model(epoch, loss, params)
        win = self.ledger.tick()
        if win is not None:
            win["nonfinite"] = nonfinite
            self.watchdog.observe(win)
        if log_every and (epoch + 1) % log_every == 0:
            parts = ["%s epoch %d" % (self.model, epoch)]
            if loss is not None:
                parts.append("loss %.6f" % loss)
            if feed is not None:
                parts.append(stall_breakdown(feed.stats()))
            if win is not None:
                parts.append("goodput %.2f binding=%s" % (
                    win["goodput"]["ratio"], win["binding"]))
            log_info("%s", " ".join(parts))
        obs.export_epoch(self.reg)
        # roll AFTER the export/publish so the epoch's full data chains
        # rode the heartbeat; this also runs the epoch-over-epoch
        # self-check (first divergence writes the replay bundle)
        self.audit.roll_epoch(epoch)
        if snapshotter is not None and snap_state is not None:
            snapshotter.capture(epoch, snap_state)
        return win
