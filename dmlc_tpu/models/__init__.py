"""Demo learner families on top of the ingest + collective stack.

The reference is a backbone library, not a model zoo — its downstream
consumers (xgboost/rabit/mxnet) supply the learners. The BASELINE north star
for this rebuild names one concrete end-to-end model — LibSVM allreduce-SGD —
so this package ships that learner family TPU-natively:

- ``linear``: logistic / squared / hinge linear models, dense or sparse-CSR
  batches, data-parallel psum gradient sync over a mesh axis
- ``fm``: factorization machines (the libfm format's model family), embedding
  table sharded or replicated, same segment-sum sparse kernels
"""

from dmlc_tpu.models.linear import (
    LinearModelParam,
    LinearLearner,
    init_linear_params,
    make_linear_train_step,
    linear_predict_dense,
)
from dmlc_tpu.models.fm import (
    FMParam,
    FMLearner,
    init_fm_params,
    make_fm_train_step,
)

__all__ = [
    "LinearModelParam",
    "LinearLearner",
    "init_linear_params",
    "make_linear_train_step",
    "linear_predict_dense",
    "FMParam",
    "FMLearner",
    "init_fm_params",
    "make_fm_train_step",
]
