"""Demo learner families on top of the ingest + collective stack.

The reference is a backbone library, not a model zoo — its downstream
consumers (xgboost/rabit/mxnet) supply the learners. The BASELINE north star
for this rebuild names one concrete end-to-end model — LibSVM allreduce-SGD —
so this package ships that learner family TPU-natively:

- ``linear``: logistic / squared / hinge linear models, dense or sparse-CSR
  batches, data-parallel psum gradient sync over a mesh axis
- ``fm``: factorization machines (the libfm format's model family), embedding
  table sharded or replicated, same segment-sum sparse kernels
- ``gbdt``: histogram gradient-boosted trees — the xgboost-over-rabit
  workload the reference backbone was built for, with per-level histogram
  psum standing in for rabit's allreduce
"""

from dmlc_tpu.models.linear import (
    LINEAR_PARTITION_RULES,
    LINEAR_MP_PARTITION_RULES,
    LinearModelParam,
    LinearLearner,
    init_linear_params,
    make_hostsync_train_step,
    make_linear_train_step,
    linear_predict_dense,
)
from dmlc_tpu.models.fm import (
    FM_PARTITION_RULES,
    FMParam,
    FMLearner,
    init_fm_params,
    make_fm_train_step,
)
from dmlc_tpu.models.gbdt import (
    GBDTLearner,
    GBDTParam,
    apply_bins,
    fit_bins,
    make_forest_builder,
    make_tree_builder,
    predict_trees,
)

__all__ = [
    "LINEAR_PARTITION_RULES",
    "LINEAR_MP_PARTITION_RULES",
    "LinearModelParam",
    "LinearLearner",
    "init_linear_params",
    "make_hostsync_train_step",
    "make_linear_train_step",
    "linear_predict_dense",
    "FM_PARTITION_RULES",
    "FMParam",
    "FMLearner",
    "init_fm_params",
    "make_fm_train_step",
    "GBDTLearner",
    "GBDTParam",
    "apply_bins",
    "fit_bins",
    "make_forest_builder",
    "make_tree_builder",
    "predict_trees",
]
