#!/usr/bin/env python
"""Headline benchmark: HIGGS-like LibSVM ingest throughput.

Measures a full pass of the sharded ingest pipeline (InputSplit chunking →
native chunk parse → CSR RowBlocks) over a deterministic synthetic HIGGS-like
file (600k rows × 28 dense features ≈ 190 MB), the same workload as the
reference's `test/libsvm_parser_test.cc` harness.

vs_baseline compares against the reference C++ parser (libsvm_parser_test,
compiled -O3, best of nthread ∈ {4,8,16}) measured on the same class of host:
334 MB/s (see BASELINE.md "measured" section).

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": "MB/s", "vs_baseline": N}
"""

import json
import os
import sys
import time

REFERENCE_MBPS = 334.0  # reference libsvm_parser_test on this host class
ROWS = 600_000
FEATURES = 28
CACHE_DIR = os.environ.get("DMLC_TPU_BENCH_DIR", "/tmp/dmlc_tpu_bench")
DATA_PATH = os.path.join(CACHE_DIR, f"higgs_like_{ROWS}.svm")


def _ensure_data() -> str:
    if os.path.exists(DATA_PATH) and os.path.getsize(DATA_PATH) > 0:
        return DATA_PATH
    os.makedirs(CACHE_DIR, exist_ok=True)
    import numpy as np

    rng = np.random.RandomState(42)
    tmp = DATA_PATH + ".tmp"
    with open(tmp, "w") as fh:
        chunk_rows = 20_000
        for start in range(0, ROWS, chunk_rows):
            n = min(chunk_rows, ROWS - start)
            labels = rng.randint(0, 2, size=n)
            vals = rng.rand(n, FEATURES)
            lines = []
            for i in range(n):
                row = vals[i]
                lines.append(
                    str(labels[i])
                    + " "
                    + " ".join(
                        f"{j + 1}:{row[j]:.6f}" for j in range(FEATURES)
                    )
                )
            fh.write("\n".join(lines) + "\n")
    os.replace(tmp, DATA_PATH)
    return DATA_PATH


def _bench_remote_ingest(path: str) -> float:
    """Loopback fake-S3 → parallel range-GET readahead → native push
    pipeline, MB/s (the Criteo-class object-store ingest shape, hermetic).
    The in-process HTTP server shares the host CPUs, so this is a floor."""
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests"))
    from fake_object_store import serve

    from dmlc_tpu.data.parsers import NativePipelineParser, create_parser
    from dmlc_tpu.io.filesystem import register_filesystem
    from dmlc_tpu.io.object_store import S3FileSystem

    server, store, base = serve()
    old_env = {k: os.environ.get(k) for k in
               ("S3_ENDPOINT", "AWS_ACCESS_KEY_ID", "AWS_SECRET_ACCESS_KEY",
                "DMLC_TPU_READAHEAD_CONNS")}
    try:
        os.environ["S3_ENDPOINT"] = base
        os.environ.pop("AWS_ACCESS_KEY_ID", None)
        os.environ.pop("AWS_SECRET_ACCESS_KEY", None)
        register_filesystem("s3://", lambda uri: S3FileSystem())
        with open(path, "rb") as fh:
            store.objects[("bench", "higgs.svm")] = fh.read()
        size = os.path.getsize(path)
        best = 0.0
        for conns in (1, 4):
            os.environ["DMLC_TPU_READAHEAD_CONNS"] = str(conns)
            t0 = time.time()
            parser = create_parser("s3://bench/higgs.svm", 0, 1, nthread=2)
            if not isinstance(parser, NativePipelineParser):
                parser.close()
                raise RuntimeError(
                    "native remote routing declined; got "
                    + type(parser).__name__
                )
            rows = sum(len(b) for b in parser)
            dt = time.time() - t0
            parser.close()
            assert rows == ROWS, f"remote row count mismatch: {rows}"
            best = max(best, size / (1 << 20) / dt)
        return best
    finally:
        server.shutdown()
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    path = _ensure_data()

    from dmlc_tpu.data import create_parser

    cpus = os.cpu_count() or 1
    threads = sorted({1, 2, min(8, max(1, cpus)), min(16, max(1, cpus))})
    best = 0.0
    for nthread in threads:
        for _trial in range(2):
            t0 = time.time()
            parser = create_parser(path, 0, 1, nthread=nthread)
            rows = 0
            nnz = 0
            for block in parser:
                rows += len(block)
                nnz += block.num_nonzero
            dt = time.time() - t0
            parser.close()
            assert rows == ROWS, f"row count mismatch: {rows}"
            assert nnz == ROWS * FEATURES, f"nnz mismatch: {nnz}"
            mbps = parser.bytes_read / (1 << 20) / dt
            best = max(best, mbps)

    extra = {}
    try:
        extra["remote_ingest_mbps"] = round(_bench_remote_ingest(path), 1)
    except Exception as err:  # the headline metric must still print
        extra["remote_ingest_error"] = str(err)
    try:
        from bench_collective import collective_metrics

        extra.update(collective_metrics())
    except Exception as err:
        extra["collective_error"] = str(err)

    print(
        json.dumps(
            {
                "metric": "higgs_libsvm_ingest",
                "value": round(best, 1),
                "unit": "MB/s",
                "vs_baseline": round(best / REFERENCE_MBPS, 3),
                "extra": extra,
            }
        )
    )


if __name__ == "__main__":
    main()
