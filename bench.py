#!/usr/bin/env python
"""Headline benchmark: HIGGS-like LibSVM ingest throughput.

Measures a full pass of the sharded ingest pipeline (InputSplit chunking →
native chunk parse → CSR RowBlocks) over a deterministic synthetic HIGGS-like
file (600k rows × 28 dense features ≈ 190 MB), the same workload as the
reference's `test/libsvm_parser_test.cc` harness.

Methodology (the numbers must be defensible on a noisy 1-core host):
- one untimed warmup pass first (builds the native lib on fresh checkouts,
  warms the page cache, primes thread pools);
- the shared vCPU's effective speed swings ~1.6x on a minutes timescale
  (measured: a fixed numpy probe ranges 1.26-2.03 GB/s over two minutes,
  and identical parse binaries score 360 vs 600 MB/s depending on the
  window). The headline therefore runs as THREE thread-config sweeps
  spread across the whole bench run; each sweep records a host-speed
  probe next to its trials, and the headline is the best sweep's best
  configuration median — the software's capability, controlled for host
  throttling. Every sweep, trial, and probe lands in `extra` so a
  drifting number can be root-caused from the JSON alone;
- the native pipeline's per-stage counters (reader/parse/consumer ns)
  for the winning configuration are reported alongside.

vs_baseline compares against the reference C++ parser (libsvm_parser_test,
compiled -O3, best of nthread ∈ {4,8,16}) measured on the same class of
host: 334 MB/s (see BASELINE.md "measured" section).

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": "MB/s", "vs_baseline": N, "extra": {...}}

Artifact discipline (round-4 lesson: the full per-sweep JSON outgrew the
driver's tail-capture window and the round's headline number survived
nowhere machine-readable): the stdout line is a COMPACT summary — headline
context, every tier's median, device status — bounded well under 2 KB.
The complete per-sweep/per-trial record is written to a detail file
(env DMLC_TPU_BENCH_DETAIL, default $DMLC_TPU_BENCH_DIR/bench_detail.json)
whose path the stdout line carries.

When the live device probe fails, the best tpu_measure.py harvest carrying
device tiers (searched: env DMLC_TPU_HARVEST_DIR, then
$DMLC_TPU_BENCH_DIR/tpu_sweep, then the repo's committed
artifacts/tpu_sweep/) is embedded under extra["harvest"] with provenance
and age, so a round-end artifact still carries device tiers measured
during a transient tunnel-up window earlier in the round.
"""

import json
import os
import statistics
import sys
import time

REFERENCE_MBPS = 334.0  # reference libsvm_parser_test on this host class
ROWS = 600_000
FEATURES = 28
TRIALS = 3
HEADLINE_TRIALS = 3  # per sweep; three sweeps are spread across main()
CACHE_DIR = os.environ.get("DMLC_TPU_BENCH_DIR", "/tmp/dmlc_tpu_bench")
DATA_PATH = os.path.join(CACHE_DIR, f"higgs_like_{ROWS}.svm")


def _ensure_data() -> str:
    if os.path.exists(DATA_PATH) and os.path.getsize(DATA_PATH) > 0:
        return DATA_PATH
    os.makedirs(CACHE_DIR, exist_ok=True)
    import numpy as np

    rng = np.random.RandomState(42)
    tmp = DATA_PATH + ".tmp"
    with open(tmp, "w") as fh:
        chunk_rows = 20_000
        for start in range(0, ROWS, chunk_rows):
            n = min(chunk_rows, ROWS - start)
            labels = rng.randint(0, 2, size=n)
            vals = rng.rand(n, FEATURES)
            lines = []
            for i in range(n):
                row = vals[i]
                lines.append(
                    str(labels[i])
                    + " "
                    + " ".join(
                        f"{j + 1}:{row[j]:.6f}" for j in range(FEATURES)
                    )
                )
            fh.write("\n".join(lines) + "\n")
    os.replace(tmp, DATA_PATH)
    return DATA_PATH


def _one_pass(path: str, nthread: int) -> tuple:
    """One timed full parse pass → (MB/s, per-stage stats dict)."""
    from dmlc_tpu.data import create_parser

    t0 = time.time()
    parser = create_parser(path, 0, 1, nthread=nthread)
    rows = 0
    nnz = 0
    for block in parser:
        rows += len(block)
        nnz += block.num_nonzero
    dt = time.time() - t0
    stats = parser.stats() if hasattr(parser, "stats") else None
    mbps = parser.bytes_read / (1 << 20) / dt
    parser.close()
    assert rows == ROWS, f"row count mismatch: {rows}"
    assert nnz == ROWS * FEATURES, f"nnz mismatch: {nnz}"
    return mbps, stats


def _device_backend_probe_once(timeout_s: float) -> tuple:
    """One jax-backend-init probe in a THROWAWAY subprocess → (ok, reason).
    When the TPU tunnel is down, jax.devices() HANGS (not errors) —
    probing in-process would wedge the whole bench and the driver would
    record nothing."""
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, (
            f"jax backend init hung past {timeout_s:.0f}s "
            "(TPU tunnel down?)"
        )
    except Exception as err:
        return False, f"backend probe failed to run: {err}"
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()
        return False, "jax backend init failed: " + (
            tail[-1] if tail else f"exit {proc.returncode}"
        )
    return True, (proc.stdout or "").strip()


def _device_backend_ok(timeout_s: float = None, attempts: int = None,
                       backoff_s: float = 20.0) -> tuple:
    """Retrying device probe → (ok, note, probe_record). A transient tunnel
    drop must not cost the round its device tiers, so a failed probe
    retries with backoff before the tiers are skipped; every attempt's
    outcome and duration goes in the JSON (probe timing is accounted here,
    SEPARATE from the tier timings — a slow init never deflates a tier's
    MB/s). Env knobs DMLC_TPU_BENCH_PROBE_ATTEMPTS/_TIMEOUT bound the
    worst-case wait (3 x 90s + backoff by default)."""
    if timeout_s is None:
        try:
            timeout_s = float(
                os.environ.get("DMLC_TPU_BENCH_PROBE_TIMEOUT", 90))
        except ValueError:  # malformed env must not cost the round its JSON
            timeout_s = 90.0
    if attempts is None:
        try:
            attempts = int(
                os.environ.get("DMLC_TPU_BENCH_PROBE_ATTEMPTS", 3))
        except ValueError:
            attempts = 3
    record = {"attempts": []}
    note = "device probe disabled (DMLC_TPU_BENCH_PROBE_ATTEMPTS < 1)"
    for i in range(attempts):
        if i:
            time.sleep(backoff_s)
        t0 = time.time()
        ok, note = _device_backend_probe_once(timeout_s)
        record["attempts"].append(
            {"ok": ok, "note": note, "secs": round(time.time() - t0, 1)}
        )
        if ok:
            return True, note, record
    return False, note, record


def _host_probe() -> float:
    """Fixed-work CPU probe (GB/s), ~0.1s; -1.0 if the probe itself fails
    (it is context for the score, never a reason to lose it). The shared
    vCPU's effective speed swings ~1.6x on a minutes timescale; a probe
    recorded next to each sweep makes that drift visible in the JSON
    instead of silently moving the score."""
    try:
        import numpy as np

        buf = getattr(_host_probe, "_buf", None)
        if buf is None:
            buf = np.random.RandomState(0).randint(
                0, 255, size=20_000_000, dtype=np.uint8
            )
            _host_probe._buf = buf
        t0 = time.perf_counter()
        for _ in range(3):
            int(buf.sum())
        return round(3 * buf.nbytes / (time.perf_counter() - t0) / 1e9, 2)
    except Exception:
        return -1.0


def _headline_threads() -> list:
    cpus = os.cpu_count() or 1
    return sorted({1, 2, min(8, max(1, cpus)), min(16, max(1, cpus))})


def _headline_sweep(path: str) -> dict:
    """One thread-config sweep → {probe_gbps, trials, stats}."""
    probe = _host_probe()
    trials = {}
    stats_by_cfg = {}
    for nthread in _headline_threads():
        runs = []
        run_stats = []
        for _ in range(HEADLINE_TRIALS):
            mbps, stats = _one_pass(path, nthread)
            runs.append(round(mbps, 1))
            run_stats.append(stats)
        trials[nthread] = runs
        # keep the stats of the median trial — the one the score reports
        median_idx = runs.index(sorted(runs)[len(runs) // 2])
        stats_by_cfg[nthread] = run_stats[median_idx]
    return {"probe_gbps": probe, "trials": trials, "stats": stats_by_cfg}


def _combine_headline(sweeps: list) -> tuple:
    """Best sweep's best configuration median → (headline, extra)."""
    best = None  # (median, sweep index, cfg)
    for i, sw in enumerate(sweeps):
        for cfg, runs in sw["trials"].items():
            med = statistics.median(runs)
            if best is None or med > best[0]:
                best = (med, i, cfg)
    headline, idx, best_cfg = best
    runs = sweeps[idx]["trials"][best_cfg]
    extra = {
        "sweeps": [
            {
                "probe_gbps": sw["probe_gbps"],
                "trials_mbps": {str(k): v for k, v in sw["trials"].items()},
            }
            for sw in sweeps
        ],
        "headline_sweep": idx,
        "headline_cfg_nthread": best_cfg,
        "headline_spread_mbps": [min(runs), max(runs)],
    }
    stats = sweeps[idx]["stats"].get(best_cfg)
    if stats:
        sec = 1e9
        extra["stages"] = {
            "chunks": stats["chunks"],
            "reader_io_s": round(stats["reader_io_ns"] / sec, 3),
            "reader_wait_s": round(stats["reader_wait_ns"] / sec, 3),
            "parse_s": round(stats["parse_ns"] / sec, 3),
            "worker_wait_s": round(stats["worker_wait_ns"] / sec, 3),
            "consumer_wait_s": round(stats["consumer_wait_ns"] / sec, 3),
        }
    return headline, extra


def _ensure_rowrec(src: str, rec: str) -> str:
    """Binary row-group twin of a text file (data/rowrec.py): the
    scan-free format — framing + memcpy — that binary shards should use.
    ``rec`` must encode the workload shape in its name (like the sources
    do) so constant bumps regenerate it rather than silently benching a
    stale conversion."""
    from dmlc_tpu.data.rowrec import convert_to_recordio

    if not (os.path.exists(rec) and os.path.getsize(rec) > 0):
        convert_to_recordio(src, rec + ".tmp", rows_per_group=4096)
        os.replace(rec + ".tmp", rec)
    return rec


def _ensure_recordio(path: str) -> str:
    return _ensure_rowrec(
        path, os.path.join(CACHE_DIR, f"higgs_like_{ROWS}.rec"))


def _rowrec_sweep(rec: str, expected_rows: int) -> dict:
    """One recordio-ingest sweep over a row-group file → {probe_gbps,
    trials} (first trial is an in-sweep warmup, dropped)."""
    from dmlc_tpu.data import create_parser

    probe = _host_probe()
    runs = []
    for _ in range(TRIALS + 1):
        t0 = time.time()
        parser = create_parser(rec, 0, 1, data_format="recordio", nthread=1)
        rows = sum(len(b) for b in parser)
        dt = time.time() - t0
        mb = parser.bytes_read / (1 << 20)
        parser.close()
        assert rows == expected_rows, f"recordio row mismatch: {rows}"
        runs.append(round(mb / dt, 1))
    return {"probe_gbps": probe, "trials": runs[1:]}


def _recordio_sweep(path: str) -> dict:
    return _rowrec_sweep(_ensure_recordio(path), ROWS)


def _ensure_criteo_recordio() -> str:
    """Binary row-group twin of the Criteo-shaped file: the sparse
    north-star workload's steady-state shard format."""
    return _ensure_rowrec(
        _ensure_criteo_like(),
        os.path.join(
            CACHE_DIR,
            f"criteo_like_{CRITEO_ROWS}x{CRITEO_NNZ}_d{CRITEO_DIM}.rec",
        ),
    )


def _criteo_recordio_sweep() -> dict:
    """One sparse binary-shard ingest sweep. Kept next to the text tier
    so the 'binary shards hold their multiple on the sparse shape' claim
    is harness-measured every round."""
    return _rowrec_sweep(_ensure_criteo_recordio(), CRITEO_ROWS)


def _ensure_shard(path: str) -> str:
    """Baked columnar twin of the higgs-shaped text file (io/shard.py,
    baked through tools/bake.py so the bench exercises the product CLI
    path). Idempotent: the bake sidecar digest skips a re-bake when the
    source and bake params are unchanged."""
    from dmlc_tpu.tools.bake import bake_dataset

    dst = os.path.join(CACHE_DIR, f"higgs_like_{ROWS}.dtsh")
    bake_dataset(path, dst, data_format="libsvm", rows_per_window=16384)
    return dst


def _shard_sweep(path: str) -> dict:
    """One baked-shard ingest sweep → {probe_gbps, trials, bake_mbps}.

    Trials are MB/s over the *shard* bytes (what the steady-state epoch
    actually reads), matching the recordio tier's accounting.
    ``bake_mbps`` is the one-off conversion cost in source-text MB/s —
    forced (not sidecar-skipped) so every sweep measures a real bake and
    the combine step can take the best window like any other score."""
    from dmlc_tpu.data import create_parser
    from dmlc_tpu.tools.bake import bake_dataset

    probe = _host_probe()
    dst = os.path.join(CACHE_DIR, f"higgs_like_{ROWS}.dtsh")
    t0 = time.time()
    bake_dataset(path, dst, data_format="libsvm", rows_per_window=16384,
                 force=True)
    bake_dt = time.time() - t0
    src_mb = os.path.getsize(path) / (1 << 20)
    runs = []
    for _ in range(TRIALS + 1):
        t0 = time.time()
        parser = create_parser(dst, 0, 1, nthread=1)
        rows = sum(len(b) for b in parser)
        dt = time.time() - t0
        mb = parser.bytes_read / (1 << 20)
        parser.close()
        assert rows == ROWS, f"shard row mismatch: {rows}"
        runs.append(round(mb / dt, 1))
    return {
        "probe_gbps": probe,
        "trials": runs[1:],
        "bake_mbps": round(src_mb / bake_dt, 1),
    }


def _combine_tier(sweeps: list) -> tuple:
    """Best sweep's score (median of its trials unless the sweep recorded
    an explicit score) → (value, sweeps-for-extra). The host is bimodal
    (BASELINE.md): a tier scored from ONE window is a coin flip, so every
    tier runs three sweeps spread across the bench and scores the best
    window — same discipline as the headline."""
    best = None
    for sw in sweeps:
        if "error" in sw or not sw.get("trials"):
            continue
        score = sw.get("score", statistics.median(sw["trials"]))
        if best is None or score > best:
            best = score
    return best, sweeps



def _bench_nthread() -> int:
    """Parse workers, native fill and device dispatch contend on small
    hosts: measured on the 1-core driver box, nthread=1 beats 2 by ~1.5x
    on the feed benches."""
    return 1 if (os.cpu_count() or 1) <= 2 else 2


def _timed_sgd_epochs(make_feed, size_mb, step_fn, layout, params, velocity,
                      stats_out=None):
    """TRIALS+1 timed epochs (first = warmup) through one jitted step —
    the single timing protocol every ingest->SGD bench in this file uses.
    ``stats_out`` (a list) collects ``feed.stats()`` for each non-warmup
    epoch — the per-stage stall breakdown next to its timing."""
    import jax

    from dmlc_tpu.models.linear import step_batch

    runs = []
    for trial in range(TRIALS + 1):
        feed = make_feed()
        t0 = time.time()
        for batch in feed:
            params, velocity, _m = step_fn(
                params, velocity, step_batch(batch, layout)
            )
        jax.block_until_ready(params)
        runs.append(round(size_mb / (time.time() - t0), 1))
        if stats_out is not None and trial > 0 and hasattr(feed, "stats"):
            stats_out.append(feed.stats())
        feed.close()
    return runs


CRITEO_ROWS = 200_000
CRITEO_DIM = 1 << 20  # hashed feature space
CRITEO_NNZ = 39  # 13 numeric + 26 categorical, Criteo shape


def _ensure_criteo_like() -> str:
    """Synthetic Criteo-shaped libsvm: 39 features/row drawn from a 2^20
    hashed id space with 7-digit ids — the high-cardinality SPARSE workload
    (the headline HIGGS file is dense-28 with 1-2 digit ids; a framework
    that only ingests that shape fast has not demonstrated the Criteo-class
    contract SURVEY §7 names)."""
    import numpy as np

    path = os.path.join(
        CACHE_DIR,
        f"criteo_like_{CRITEO_ROWS}x{CRITEO_NNZ}_d{CRITEO_DIM}.svm",
    )
    if os.path.exists(path) and os.path.getsize(path) > 0:
        return path
    os.makedirs(CACHE_DIR, exist_ok=True)
    rng = np.random.RandomState(7)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        for start in range(0, CRITEO_ROWS, 10_000):
            n = min(10_000, CRITEO_ROWS - start)
            labels = rng.randint(0, 2, size=n)
            ids = rng.randint(0, CRITEO_DIM, size=(n, CRITEO_NNZ))
            ids.sort(axis=1)
            vals = rng.rand(n, CRITEO_NNZ)
            lines = []
            for i in range(n):
                lines.append(
                    str(labels[i]) + " " + " ".join(
                        f"{ids[i, j]}:{vals[i, j]:.4f}"
                        for j in range(CRITEO_NNZ)
                    )
                )
            fh.write("\n".join(lines) + "\n")
    os.replace(tmp, path)
    return path


def _criteo_parse_sweep() -> dict:
    """One sparse high-cardinality parse sweep over the Criteo-shaped file
    → {probe_gbps, trials} (first trial is an in-sweep warmup, dropped).
    The {1,2}-thread configs both run; the sweep's trials are the better
    config's (mirroring the headline's per-config discipline at the
    1-core-host scale)."""
    from dmlc_tpu.data import create_parser

    path = _ensure_criteo_like()
    size_mb = os.path.getsize(path) / (1 << 20)
    probe = _host_probe()
    best_runs, best_med = None, -1.0
    for nthread in sorted({1, _bench_nthread()}):
        runs = []
        for _ in range(TRIALS + 1):
            t0 = time.time()
            parser = create_parser(path, 0, 1, nthread=nthread)
            rows = sum(len(b) for b in parser)
            dt = time.time() - t0
            parser.close()
            assert rows == CRITEO_ROWS, f"criteo row count mismatch: {rows}"
            runs.append(round(size_mb / dt, 1))
        med = statistics.median(runs[1:])
        if med > best_med:
            best_runs, best_med = runs[1:], med
    return {"probe_gbps": probe, "trials": best_runs}


# parse-stage corpora, read/synthesized once per run and kept in memory so
# every parse_only sweep times parse_chunk ALONE — no file I/O, no pipeline
# threads, no per-sweep page-cache variance
_PARSE_ONLY_CORPUS: dict = {}


def _parse_only_corpora() -> dict:
    if _PARSE_ONLY_CORPUS:
        return _PARSE_ONLY_CORPUS
    import numpy as np

    def _chunks(raw: bytes, target: int) -> list:
        out, pos = [], 0
        while pos < len(raw):
            cut = raw.rfind(b"\n", pos, pos + target) + 1
            if cut <= pos:  # no newline in window: take the rest
                cut = len(raw)
            out.append(raw[pos:cut])
            pos = cut
        return out

    with open(_ensure_data(), "rb") as fh:
        svm = fh.read(64 << 20)
    svm = svm[: svm.rfind(b"\n") + 1]
    _PARSE_ONLY_CORPUS["libsvm"] = _chunks(svm, 8 << 20)

    # dense CSV corpus, higgs-shaped (label + FEATURES columns), ~24 MB
    rng = np.random.RandomState(11)
    rows = []
    for start in range(0, 120_000, 20_000):
        labels = rng.randint(0, 2, size=20_000)
        vals = rng.rand(20_000, FEATURES)
        for i in range(20_000):
            rows.append(
                str(labels[i]) + ","
                + ",".join(f"{v:.4f}" for v in vals[i])
            )
    csv = ("\n".join(rows) + "\n").encode()
    _PARSE_ONLY_CORPUS["csv"] = _chunks(csv, 8 << 20)
    return _PARSE_ONLY_CORPUS


def _parse_only_sweep() -> dict:
    """Parse-STAGE microbench: in-memory chunks through parse_chunk per
    (format, backend), nothing else on the clock. The tier's trials (and
    so parse_only_mbps) are the production libsvm path — native when the
    core is loaded, else the python vector path; per-backend medians land
    in ``formats`` as ``*_gbps`` and are lifted into extra for the sentry.
    The python backends time a single chunk (they are 20-60 MB/s; the
    point is tracking the ratio, not burning bench wall-clock)."""
    from dmlc_tpu import native
    from dmlc_tpu.data import vparse
    from dmlc_tpu.data.parsers import _native_libsvm
    from dmlc_tpu.data.row_block import RowBlockContainer

    corpora = _parse_only_corpora()
    probe = _host_probe()

    def _time(chunks, fn):
        mb = sum(len(c) for c in chunks) / (1 << 20)
        runs = []
        for _ in range(TRIALS + 1):  # first is warmup, dropped
            t0 = time.time()
            for chunk in chunks:
                fn(chunk)
            runs.append(round(mb / (time.time() - t0), 1))
        return runs[1:]

    formats: dict = {}
    trials = None
    native_on = native.available()
    if native_on:
        runs = _time(corpora["libsvm"], _native_libsvm)
        trials = runs
        formats["libsvm_native_gbps"] = round(
            statistics.median(runs) / 1024, 3)
        csv_runs = _time(
            corpora["csv"], lambda c: native.parse_csv_chunk(c))
        formats["csv_native_gbps"] = round(
            statistics.median(csv_runs) / 1024, 3)
    vec_runs = _time(
        corpora["libsvm"][:1],
        lambda c: vparse.parse_libsvm_vector(c, RowBlockContainer()),
    )
    formats["libsvm_vector_gbps"] = round(
        statistics.median(vec_runs) / 1024, 3)
    csv_vec = _time(corpora["csv"][:1], vparse.parse_csv_vector_table)
    formats["csv_vector_gbps"] = round(statistics.median(csv_vec) / 1024, 3)
    if trials is None:
        trials = vec_runs
    return {"probe_gbps": probe, "trials": trials, "formats": formats,
            "native": native_on}


def _bench_criteo_sgd() -> dict:
    """Criteo sparse END-TO-END on the attached device: parse → sharded-COO
    staging → csr train step (segment-sum SpMV grads over the 2^20 feature
    space) → SGD — the north-star workload's device loop."""
    import jax.numpy as jnp

    from dmlc_tpu.data import create_parser
    from dmlc_tpu.device import BatchSpec, DeviceFeed
    from dmlc_tpu.models.linear import (
        init_linear_params,
        make_linear_train_step,
    )

    path = _ensure_criteo_like()
    size_mb = os.path.getsize(path) / (1 << 20)
    nthread = _bench_nthread()
    # auto bucket: the sixteenth-octave policy (device/csr.round_up_bucket)
    # pads ~2.5% on this shape vs 64% at the old fixed pow2 bucket —
    # measured +22% on this tier
    spec = BatchSpec(batch_size=8192, layout="csr",
                     num_features=CRITEO_DIM + 1)
    step = make_linear_train_step(
        None, learning_rate=0.05, layout="csr",
        num_features=CRITEO_DIM + 1, donate_batch=True,
    )
    params = init_linear_params(CRITEO_DIM + 1)
    velocity = {k: jnp.zeros_like(v) for k, v in params.items()}
    sgd_runs = _timed_sgd_epochs(
        lambda: DeviceFeed(create_parser(path, 0, 1, nthread=nthread), spec),
        size_mb, step, "csr", params, velocity,
    )
    return {
        "criteo_like_csr_sgd_mbps": round(statistics.median(sgd_runs[1:]), 1),
        "criteo_like_csr_sgd_trials_mbps": sgd_runs[1:],
    }


def _bench_gbdt(path: str) -> dict:
    """Histogram-GBDT boosting rate on the attached device — the
    xgboost-over-rabit workload (models/gbdt.py) measured per the
    harness-or-it-didn't-happen bar. Metric = boosted row-visits per
    second (rows × trees / fit wall; each fit re-bins, a few percent of
    the wall on this shape): the histogram build (segment-sum + cumsum
    split finding) dominates, the same profile distributed xgboost
    allreduces. One learner serves every trial so the warmup fit
    genuinely absorbs the tree-builder jit compile (fresh learners would
    recompile per trial and score compile time as throughput)."""
    import numpy as np

    from dmlc_tpu.data import create_parser
    from dmlc_tpu.models.gbdt import GBDTLearner

    rows_cap = 131_072
    parser = create_parser(path, 0, 1, nthread=1)
    xs, ys, seen = [], [], 0
    try:
        for block in parser:
            xs.append(block.to_dense(FEATURES + 1))  # 1-based ids
            ys.append(np.asarray(block.label, dtype=np.float32))
            seen += len(block)
            if seen >= rows_cap:
                break
    finally:
        parser.close()
    x = np.concatenate(xs)[:rows_cap]
    y = np.concatenate(ys)[:rows_cap]
    trees, depth = 8, 6
    runs = []
    learner = GBDTLearner(num_trees=trees, max_depth=depth,
                          learning_rate=0.3, num_bins=64)
    for _ in range(TRIALS + 1):  # first = jit compile warmup
        t0 = time.time()
        history = learner.fit(x, y)
        dt = time.time() - t0
        assert np.all(np.isfinite(history)), history
        runs.append(round(x.shape[0] * trees / dt / 1e6, 2))
    return {
        "gbdt_fit_mrows_s": statistics.median(runs[1:]),
        "gbdt_fit_trials_mrows_s": runs[1:],
        "gbdt_shape": f"{x.shape[0]}x{x.shape[1]} t{trees} d{depth} b64",
    }


def _bench_recordio_sgd(path: str) -> dict:
    """Recordio row-group → native StageBatch → dense SGD on the attached
    device: the scan-free binary ingest path driven all the way to the
    chip (host-side it parses at GB/s; this tier proves that throughput
    survives to the training loop instead of dying before H2D)."""
    import jax.numpy as jnp

    from dmlc_tpu.data import create_parser
    from dmlc_tpu.device import BatchSpec, DeviceFeed
    from dmlc_tpu.models.linear import (
        init_linear_params,
        make_linear_train_step,
    )

    rec = _ensure_recordio(path)
    size_mb = os.path.getsize(rec) / (1 << 20)
    spec = BatchSpec(batch_size=16384, layout="dense", num_features=29)
    params = init_linear_params(29)
    velocity = {k: jnp.zeros_like(v) for k, v in params.items()}
    step = make_linear_train_step(None, learning_rate=0.1, layout="dense",
                                  donate_batch=True)
    runs = _timed_sgd_epochs(
        lambda: DeviceFeed(
            create_parser(rec, 0, 1, data_format="recordio", nthread=1),
            spec,
        ),
        size_mb, step, "dense", params, velocity,
    )
    return {
        "recordio_sgd_mbps": round(statistics.median(runs[1:]), 1),
        "recordio_sgd_trials_mbps": runs[1:],
    }


def _bench_shard_sgd(path: str) -> dict:
    """Baked columnar shard → dense SGD on the attached device: the
    ISSUE's 'ingest at RecordIO speed' claim measured end-to-end. Scored
    in *source-text* MB/s (same ``size_mb`` as sgd_e2e_mbps) so the
    sentry compares it directly against the text-parse epoch — the baked
    epoch must beat it or the format isn't paying for itself."""
    import jax.numpy as jnp

    from dmlc_tpu.data import create_parser
    from dmlc_tpu.device import BatchSpec, DeviceFeed
    from dmlc_tpu.models.linear import (
        init_linear_params,
        make_linear_train_step,
    )

    shard = _ensure_shard(path)
    size_mb = os.path.getsize(path) / (1 << 20)
    spec = BatchSpec(batch_size=16384, layout="dense", num_features=29)
    params = init_linear_params(29)
    velocity = {k: jnp.zeros_like(v) for k, v in params.items()}
    step = make_linear_train_step(None, learning_rate=0.1, layout="dense",
                                  donate_batch=True)
    runs = _timed_sgd_epochs(
        lambda: DeviceFeed(create_parser(shard, 0, 1, nthread=1), spec),
        size_mb, step, "dense", params, velocity,
    )
    return {
        "sgd_e2e_shard_mbps": round(statistics.median(runs[1:]), 1),
        "sgd_e2e_shard_trials_mbps": runs[1:],
    }


def _median_stall_stages(stats_list) -> dict:
    """Median per-stage stall breakdown (seconds) over the non-warmup
    epochs' ``DeviceFeed.stats()`` records, pool/parse counters included —
    the 'where did the pipelined epoch's time go' artifact field."""
    if not stats_list:
        return {}
    out = {}
    for key in ("host_batch_ns", "dispatch_ns", "host_wait_ns",
                "consume_ns"):
        vals = [s.get(key, 0) for s in stats_list]
        out[key.replace("_ns", "_s")] = round(
            statistics.median(vals) / 1e9, 3)
    pools = [s.get("pool") or {} for s in stats_list]
    out["pool_allocated"] = int(statistics.median(
        [p.get("allocated", 0) for p in pools]))
    out["pool_reused"] = int(statistics.median(
        [p.get("reused", 0) for p in pools]))
    pipes = [s.get("pipeline") or {} for s in stats_list]
    if any(p.get("chunks") for p in pipes):
        out["parse_s"] = round(statistics.median(
            [p.get("parse_ns", 0) for p in pipes]) / 1e9, 3)
        out["parse_wait_s"] = round(statistics.median(
            [p.get("consumer_wait_ns", 0) for p in pipes]) / 1e9, 3)
    return out


def _bench_device_feed(path: str) -> dict:
    """Feed-only (parse→densify→H2D) and ingest→SGD MB/s on the attached
    accelerator, median of warm passes (the jitted step persists across
    passes — steady-state epochs, not first-compile)."""
    import jax

    from dmlc_tpu.data.parsers import create_parser
    from dmlc_tpu.device.feed import BatchSpec, DeviceFeed
    from dmlc_tpu.models.linear import (
        init_linear_params,
        make_linear_train_step,
        step_batch,
    )
    import jax.numpy as jnp

    size_mb = os.path.getsize(path) / (1 << 20)
    spec = BatchSpec(batch_size=16384, layout="dense", num_features=29)
    nthread = _bench_nthread()

    def _feed(feed_spec=spec):
        return DeviceFeed(
            create_parser(path, 0, 1, nthread=nthread), feed_spec
        )

    # feed-only at prefetch 1 vs 2: through a tunneled runtime each
    # dispatch pays real latency, so a second batch in flight may hide
    # it — the A/B lands in the artifact so the better window is known
    # per-deployment, not guessed
    feed_runs = []
    prefetch_ab = {}
    stage_samples = {"host_batch_ns": [], "dispatch_ns": [],
                     "host_wait_ns": []}
    for depth in (1, 2):
        depth_spec = BatchSpec(batch_size=16384, layout="dense",
                               num_features=29, prefetch=depth)
        runs = []
        for trial in range(TRIALS + 1):  # first is compile/cache warmup
            feed = _feed(depth_spec)
            t0 = time.time()
            last = None
            for batch in feed:
                last = batch
            jax.block_until_ready(last["x"])
            runs.append(round(size_mb / (time.time() - t0), 1))
            stats = feed.stats()
            if trial > 0 and depth == 1:  # stage medians at the base depth
                for key in stage_samples:
                    stage_samples[key].append(stats[key])
            feed.close()
        prefetch_ab[f"feed_dense_prefetch{depth}_trials_mbps"] = runs[1:]
        if depth == 1:
            feed_runs = runs
    feed_stages = {
        key.replace("_ns", "_s"): round(statistics.median(vals) / 1e9, 3)
        for key, vals in stage_samples.items()
    }

    params = init_linear_params(29)
    velocity = {"w": jnp.zeros_like(params["w"]),
                "b": jnp.zeros_like(params["b"])}
    step = make_linear_train_step(None, learning_rate=0.1, layout="dense",
                                  donate_batch=True)
    sgd_runs = _timed_sgd_epochs(
        _feed, size_mb, step, "dense", params, velocity
    )

    # tentpole A/B: fully-serial ingest (threaded=False parser — no parse
    # fan-out, no host prefetch thread, one transfer in flight) vs the
    # async pipeline (chunk-parse workers + host prefetch + transfer
    # window 2). Same step, same data: the spread IS the overlap win, and
    # the pipelined epochs' stage breakdown says where remaining time sat.
    sparams = init_linear_params(29)
    svel = {"w": jnp.zeros_like(sparams["w"]),
            "b": jnp.zeros_like(sparams["b"])}
    serial_spec = BatchSpec(batch_size=16384, layout="dense",
                            num_features=29, prefetch=1)
    serial_runs = _timed_sgd_epochs(
        lambda: DeviceFeed(
            create_parser(path, 0, 1, nthread=1, threaded=False),
            serial_spec, host_prefetch=0,
        ),
        size_mb, step, "dense", sparams, svel,
    )
    pparams = init_linear_params(29)
    pvel = {"w": jnp.zeros_like(pparams["w"]),
            "b": jnp.zeros_like(pparams["b"])}
    pipe_spec = BatchSpec(batch_size=16384, layout="dense",
                          num_features=29, prefetch=2)
    pipe_stats: list = []
    pipe_runs = _timed_sgd_epochs(
        lambda: DeviceFeed(
            create_parser(path, 0, 1, nthread=max(2, nthread)),
            pipe_spec, host_prefetch=2,
        ),
        size_mb, step, "dense", pparams, pvel, stats_out=pipe_stats,
    )

    # the same text uri with #cachefile: epoch 1 builds a row-group cache
    # (DiskRowIter semantics, disk_row_iter.h:95-141), warm epochs stream
    # binary — the reference's own answer to per-epoch text-parse tax,
    # here at the native recordio rate. Scored like every tier: warmup
    # epoch (the build) dropped, median of warm epochs.
    cache_uri = path + "#" + os.path.join(CACHE_DIR, "higgs_sgd_cache.rec")
    kparams = init_linear_params(29)
    kvel = {"w": jnp.zeros_like(kparams["w"]),
            "b": jnp.zeros_like(kparams["b"])}
    cached_runs = _timed_sgd_epochs(
        lambda: DeviceFeed(
            create_parser(cache_uri, 0, 1, nthread=nthread), spec
        ),
        size_mb, step, "dense", kparams, kvel,
    )

    # sparse path e2e: csr layout (native COO staging) through the csr
    # train step — the genuinely-sparse Criteo-class shape
    cparams = init_linear_params(29)
    cvel = {"w": jnp.zeros_like(cparams["w"]),
            "b": jnp.zeros_like(cparams["b"])}
    csr_step = make_linear_train_step(
        None, learning_rate=0.1, layout="csr", num_features=29,
        donate_batch=True,
    )
    csr_spec = BatchSpec(batch_size=16384, layout="csr", num_features=29)
    csr_runs = _timed_sgd_epochs(
        lambda: _feed(csr_spec), size_mb, csr_step, "csr", cparams, cvel
    )

    # device-resident fast path A/B (DMLC_TPU_DEVICE_RESIDENT): the
    # pad-in-place emit rides the python re-batch producer, so both arms
    # pin the vector parse backend — the spread isolates the staging fuse
    # (+ donation arena reuse) from the parser choice. The default-path
    # sgd_e2e_mbps key above stays untouched for A/B history.
    # h2d_overlap_ratio: the fraction of the resident epoch's wall time
    # NOT booked to transfer dispatch or waiting on the host producer —
    # 1.0 means H2D fully hidden behind parse + step (sentry-gated
    # higher-is-better, BENCH_DIRECTIONS).
    resident_spec = BatchSpec(batch_size=16384, layout="dense",
                              num_features=29, prefetch=2)
    saved_env = {k: os.environ.get(k)
                 for k in ("DMLC_TPU_DEVICE_RESIDENT",
                           "DMLC_TPU_PARSE_BACKEND")}
    resident_stats: list = []
    try:
        os.environ["DMLC_TPU_PARSE_BACKEND"] = "vector"
        os.environ.pop("DMLC_TPU_DEVICE_RESIDENT", None)
        yparams = init_linear_params(29)
        yvel = {"w": jnp.zeros_like(yparams["w"]),
                "b": jnp.zeros_like(yparams["b"])}
        python_runs = _timed_sgd_epochs(
            lambda: DeviceFeed(
                create_parser(path, 0, 1, nthread=max(2, nthread)),
                resident_spec,
            ),
            size_mb, step, "dense", yparams, yvel,
        )
        os.environ["DMLC_TPU_DEVICE_RESIDENT"] = "1"
        rparams = init_linear_params(29)
        rvel = {"w": jnp.zeros_like(rparams["w"]),
                "b": jnp.zeros_like(rparams["b"])}
        resident_runs = _timed_sgd_epochs(
            lambda: DeviceFeed(
                create_parser(path, 0, 1, nthread=max(2, nthread)),
                resident_spec,
            ),
            size_mb, step, "dense", rparams, rvel,
            stats_out=resident_stats,
        )
    finally:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    overlap_samples = []
    for mbps, stats in zip(resident_runs[1:], resident_stats):
        wall_s = size_mb / max(mbps, 1e-9)
        busy_s = (stats.get("dispatch_ns", 0)
                  + stats.get("host_wait_ns", 0)) / 1e9
        overlap_samples.append(max(0.0, min(1.0, 1.0 - busy_s / wall_s)))
    # binding verdict for the resident arm from its own stall ledger:
    # host_wait = waiting on parse, dispatch = H2D submission, consume =
    # the jitted step. The fast path's acceptance is that this lands on
    # parse or device_step, not h2d/host_wait-as-transfer.
    rstages = _median_stall_stages(resident_stats)
    rscores = {
        "parse": rstages.get("host_wait_s", 0.0) + rstages.get("parse_s", 0.0),
        "h2d": rstages.get("dispatch_s", 0.0),
        "device_step": rstages.get("consume_s", 0.0),
    }
    resident_binding = max(rscores, key=rscores.get)

    out = {
        "feed_dense_mbps": round(statistics.median(feed_runs[1:]), 1),
        "feed_dense_trials_mbps": feed_runs[1:],
        **prefetch_ab,
        "feed_stages": feed_stages,
        "sgd_e2e_mbps": round(statistics.median(sgd_runs[1:]), 1),
        "sgd_e2e_trials_mbps": sgd_runs[1:],
        "sgd_e2e_serial_mbps": round(statistics.median(serial_runs[1:]), 1),
        "sgd_e2e_serial_trials_mbps": serial_runs[1:],
        "sgd_e2e_pipelined_mbps": round(statistics.median(pipe_runs[1:]), 1),
        "sgd_e2e_pipelined_trials_mbps": pipe_runs[1:],
        "pipelined_stall_stages": _median_stall_stages(pipe_stats),
        "sgd_e2e_cached_mbps": round(statistics.median(cached_runs[1:]), 1),
        "sgd_e2e_cached_trials_mbps": cached_runs[1:],
        "sgd_csr_e2e_mbps": round(statistics.median(csr_runs[1:]), 1),
        "sgd_csr_e2e_trials_mbps": csr_runs[1:],
        "sgd_e2e_python_mbps": round(statistics.median(python_runs[1:]), 1),
        "sgd_e2e_python_trials_mbps": python_runs[1:],
        "sgd_e2e_resident_mbps": round(
            statistics.median(resident_runs[1:]), 1),
        "sgd_e2e_resident_trials_mbps": resident_runs[1:],
        "h2d_overlap_ratio": (
            round(statistics.median(overlap_samples), 3)
            if overlap_samples else 0.0
        ),
        "resident_stall_stages": rstages,
        "resident_binding_stage": resident_binding,
        "device": str(jax.devices()[0].platform),
    }
    # Sharded sparse H2D accounting (one batch, host-side): per-device
    # entry bytes under the 8-shard partition vs the replicated layout.
    # Native-only (the sharded fill lives in pipeline.cc); its absence
    # must not discard the timing metrics above.
    try:
        parser = create_parser(path, 0, 1, nthread=nthread)
        try:
            if hasattr(parser, "read_batch_coo_sharded"):
                batch_rows, shards = 16384, 8
                sharded = parser.read_batch_coo_sharded(batch_rows, shards)
                out["csr_batch_nnz"] = sharded.num_nonzero
                out["csr_nnz_per_device_8shard"] = sharded.nnz_bucket
                # shipped per entry: indices + values (8 B); the row
                # mapping crosses H2D as per-shard CSR offsets (4 B/row),
                # not per-entry row_ids (device/feed._put_csr)
                rows_local = batch_rows // shards
                out["csr_h2d_bytes_per_device"] = (
                    sharded.nnz_bucket * 8 + (rows_local + 1) * 4
                )
                out["csr_h2d_bytes_per_device_replicated"] = (
                    sharded.num_nonzero * 8 + (batch_rows + 1) * 4
                )
        finally:
            parser.close()
    except Exception as err:  # keep the timing metrics measured above
        out["csr_shard_accounting_error"] = str(err)
    return out


def _remote_sweep(path: str) -> dict:
    """One loopback fake-S3 → parallel range-GET readahead → native push
    pipeline sweep → {probe_gbps, trials, score, conns} (the Criteo-class
    object-store ingest shape, hermetic). The in-process HTTP server shares
    the host CPUs, so every number here is a floor. Score = the better
    connection-count config's median."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tests"))
    from fake_object_store import serve

    from dmlc_tpu.data.parsers import NativePipelineParser, create_parser
    from dmlc_tpu.io.filesystem import register_filesystem
    from dmlc_tpu.io.object_store import S3FileSystem

    server, store, base = serve()
    old_env = {k: os.environ.get(k) for k in
               ("S3_ENDPOINT", "AWS_ACCESS_KEY_ID", "AWS_SECRET_ACCESS_KEY",
                "DMLC_TPU_READAHEAD_CONNS")}
    probe = _host_probe()
    try:
        os.environ["S3_ENDPOINT"] = base
        os.environ.pop("AWS_ACCESS_KEY_ID", None)
        os.environ.pop("AWS_SECRET_ACCESS_KEY", None)
        register_filesystem("s3://", lambda uri: S3FileSystem())
        with open(path, "rb") as fh:
            store.objects[("bench", "higgs.svm")] = fh.read()
        size = os.path.getsize(path)
        nthread = 1 if (os.cpu_count() or 1) <= 2 else 2
        best = None  # (median, runs, conns)
        for conns in (1, 4):
            os.environ["DMLC_TPU_READAHEAD_CONNS"] = str(conns)
            runs = []
            for _ in range(2):
                t0 = time.time()
                parser = create_parser(
                    "s3://bench/higgs.svm", 0, 1, nthread=nthread
                )
                if not isinstance(parser, NativePipelineParser):
                    parser.close()
                    raise RuntimeError(
                        "native remote routing declined; got "
                        + type(parser).__name__
                    )
                rows = sum(len(b) for b in parser)
                dt = time.time() - t0
                parser.close()
                assert rows == ROWS, f"remote row count mismatch: {rows}"
                runs.append(round(size / (1 << 20) / dt, 1))
            med = statistics.median(runs)
            if best is None or med > best[0]:
                best = (med, runs, conns)
        return {"probe_gbps": probe, "trials": best[1],
                "score": best[0], "conns": best[2]}
    finally:
        server.shutdown()
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


class _TenantFeed:
    """One tenant's DeviceFeed over a fresh two-job dispatcher fleet.

    Each construction is one epoch of the multi-tenant shape: job
    ``train`` drives the jitted SGD step through this feed while job
    ``aux`` — same source, its own ledger — is drained concurrently by a
    background thread. close() tears the whole fleet down, so the
    _timed_sgd_epochs protocol (fresh feed per epoch) measures fleet
    bring-up + contended serving, not a warm single-tenant pipe."""

    def __init__(self, path, spec, nworkers=2, nchunks=8):
        import threading

        from dmlc_tpu.data import (BlockService, DataDispatcher,
                                   RemoteBlockParser)
        from dmlc_tpu.device.feed import DeviceFeed

        self._disp = DataDispatcher()
        self._disp.add_job("train", path, nchunks=nchunks)
        self._disp.add_job("aux", path, nchunks=nchunks)
        self._workers = [
            BlockService(dispatcher=self._disp.address,
                         nthread=_bench_nthread())
            for _ in range(nworkers)
        ]
        self.aux_rows = 0

        def _drain_aux():
            try:
                aux = RemoteBlockParser(self._disp.address, dispatcher=True,
                                        job="aux")
                for block in aux:
                    self.aux_rows += len(block)
                aux.close()
            except Exception:  # the aux tenant must not fail the timing
                pass

        self._aux_thread = threading.Thread(target=_drain_aux, daemon=True)
        self._aux_thread.start()
        self._feed = DeviceFeed(
            RemoteBlockParser(self._disp.address, dispatcher=True,
                              job="train"),
            spec,
        )

    def __iter__(self):
        return iter(self._feed)

    def stats(self):
        return self._feed.stats()

    def close(self):
        self._feed.close()
        self._aux_thread.join(timeout=60)
        for svc in self._workers:
            svc.close()
        self._disp.close()


def _bench_multijob(path: str) -> dict:
    """Multi-tenant fleet tiers: ingest→SGD with a second tenant live on
    the same dispatcher (sgd_e2e_multijob_mbps), and the cross-job
    source-cache hit ratio — a fresh fleet serves the source to one job
    cold, then to a second job that should parse NOTHING
    (cache_cross_job_hit_ratio = 1.0 is the PR 12 acceptance bar)."""
    import jax.numpy as jnp

    from dmlc_tpu.data import (BlockService, DataDispatcher,
                               RemoteBlockParser, reset_source_cache,
                               source_cache)
    from dmlc_tpu.device.feed import BatchSpec
    from dmlc_tpu.models.linear import (
        init_linear_params,
        make_linear_train_step,
    )

    size_mb = os.path.getsize(path) / (1 << 20)
    # the shared cache must hold the whole parsed source or the warm
    # tenant re-parses evicted parts; budget ~4x text size, restored after
    old_cache_mb = os.environ.get("DMLC_TPU_DATA_CACHE_MB")
    os.environ["DMLC_TPU_DATA_CACHE_MB"] = str(
        max(256, int(size_mb * 4) + 64))
    reset_source_cache()
    try:
        spec = BatchSpec(batch_size=16384, layout="dense", num_features=29)
        params = init_linear_params(29)
        velocity = {"w": jnp.zeros_like(params["w"]),
                    "b": jnp.zeros_like(params["b"])}
        step = make_linear_train_step(None, learning_rate=0.1,
                                      layout="dense", donate_batch=True)
        runs = _timed_sgd_epochs(
            lambda: _TenantFeed(path, spec), size_mb, step, "dense",
            params, velocity,
        )

        # cold/warm cache pass on a fresh fleet: ONE worker so every part
        # leased for the warm job is resident where it was parsed. Both
        # ledgers are registered up front (a worker whose whole fleet
        # drains retires its stream), then drained one after the other.
        reset_source_cache()
        nchunks = 8
        with DataDispatcher() as disp:
            disp.add_job("cold", path, nchunks=nchunks)
            disp.add_job("warm", path, nchunks=nchunks)
            with BlockService(dispatcher=disp.address,
                              nthread=_bench_nthread()) as svc:
                cold = RemoteBlockParser(disp.address, dispatcher=True,
                                         job="cold")
                cold_rows = sum(len(b) for b in cold)
                cold.close()
                hits_before = source_cache().hits
                parsed_before = svc.chunks_parsed
                warm = RemoteBlockParser(disp.address, dispatcher=True,
                                         job="warm")
                warm_rows = sum(len(b) for b in warm)
                warm.close()
                hit_ratio = (source_cache().hits - hits_before) / nchunks
                warm_parsed = svc.chunks_parsed - parsed_before
        assert warm_rows == cold_rows, "tenants saw different row counts"
        return {
            "sgd_e2e_multijob_mbps": round(statistics.median(runs[1:]), 1),
            "sgd_e2e_multijob_trials_mbps": runs[1:],
            "cache_cross_job_hit_ratio": round(hit_ratio, 3),
            "cache_cross_job_warm_parses": warm_parsed,
        }
    finally:
        if old_cache_mb is None:
            os.environ.pop("DMLC_TPU_DATA_CACHE_MB", None)
        else:
            os.environ["DMLC_TPU_DATA_CACHE_MB"] = old_cache_mb
        reset_source_cache()


def _bench_snapshot(path: str) -> dict:
    """Preemption-proof snapshot overhead: the SAME ingest→SGD epoch
    armed with async job snapshots vs unarmed (ckpt_overhead_ratio —
    the ≤5% acceptance bar), plus the wall time a relaunched run pays
    to restore the committed snapshot (resume_restore_s). Both are
    sentry-gated lower-is-better."""
    import shutil
    import tempfile

    from dmlc_tpu.collective.checkpoint import JobSnapshot
    from dmlc_tpu.collective.snapshot import load_snapshot
    from dmlc_tpu.models.linear import LinearLearner

    def _fit_s(snapshot_uri=None):
        learner = LinearLearner(learning_rate=0.1)
        t0 = time.time()
        learner.fit_uri(path, batch_size=16384, epochs=1, num_features=29,
                        snapshot_uri=snapshot_uri)
        return time.time() - t0

    snap_dir = tempfile.mkdtemp(prefix="dmlc-bench-snap-")
    try:
        unarmed = [_fit_s() for _ in range(TRIALS + 1)][1:]
        armed = [
            _fit_s(snapshot_uri=os.path.join(snap_dir, f"t{trial}"))
            for trial in range(TRIALS + 1)
        ][1:]
        base_s = statistics.median(unarmed)
        armed_s = statistics.median(armed)
        snap = JobSnapshot(os.path.join(snap_dir, f"t{TRIALS}"))
        t0 = time.time()
        version, _state, _meta = load_snapshot(snap)
        restore_s = time.time() - t0
        return {
            "ckpt_overhead_ratio": round(
                max(0.0, armed_s / base_s - 1.0), 4),
            "resume_restore_s": round(restore_s, 4),
            "snapshot_restored_version": version,
            "snapshot_unarmed_trials_s": [round(v, 3) for v in unarmed],
            "snapshot_armed_trials_s": [round(v, 3) for v in armed],
        }
    finally:
        shutil.rmtree(snap_dir, ignore_errors=True)


# keys lifted verbatim from the full record into the compact stdout line:
# every tier median + device/collective status the verdict reads
_COMPACT_KEYS = (
    "recordio_ingest_mbps", "criteo_like_parse_mbps",
    "parse_only_mbps", "parse_only_libsvm_native_gbps",
    "parse_only_libsvm_vector_gbps", "parse_only_csv_native_gbps",
    "parse_only_csv_vector_gbps",
    "criteo_recordio_ingest_mbps", "shard_ingest_gbps", "bake_mbps",
    "remote_ingest_mbps",
    "feed_dense_mbps", "sgd_e2e_mbps", "sgd_e2e_serial_mbps",
    "sgd_e2e_pipelined_mbps", "sgd_e2e_cached_mbps",
    "sgd_csr_e2e_mbps", "recordio_sgd_mbps", "sgd_e2e_shard_mbps",
    "criteo_like_csr_sgd_mbps",
    "sgd_e2e_resident_mbps", "sgd_e2e_python_mbps", "h2d_overlap_ratio",
    "resident_binding_stage",
    "gbdt_fit_mrows_s",
    "sgd_e2e_multijob_mbps", "cache_cross_job_hit_ratio",
    "sgd_goodput_ratio", "sgd_mfu", "ckpt_overhead_ratio",
    "resume_restore_s",
    "device", "device_feed_probe_gbps", "device_feed_probe_gbps_post",
    "device_tier_probes_gbps",
    "socket_tree_64k_gbps", "socket_ring_8m_gbps", "socket_world",
    "socket_note", "psum_single_device_gbps", "psum_step_ms",
    "psum_devices", "psum_platform", "psum_algo_gbps",
    "psum_ici_utilization", "spmd_psum_step_gbps", "spmd_step_ms",
    "spmd_devices", "spmd_platform", "ici_utilization",
    "bucket_fused_ms", "bucket_per_tensor_ms",
    "engine_allreduce_gbps", "engine_reduce_single_process_gbps",
    "headline_cfg_nthread", "headline_spread_mbps", "headline_sweep",
)


# sentry direction registry carried on every record (obs/sentry.py
# record_directions): extra keys the gate scores that no suffix rule
# covers — both are 0..1 fractions, higher is better
BENCH_DIRECTIONS = {
    "sgd_goodput_ratio": "higher",
    "h2d_overlap_ratio": "higher",
    # snapshot tax and restore latency regress upward: gate them down
    "ckpt_overhead_ratio": "lower",
    "resume_restore_s": "lower",
    # model FLOP utilization of the whole-run goodput window
    # (obs/xla_cost.py analytics over the peak-FLOPs ceiling)
    "sgd_mfu": "higher",
}


# a harvest is only worth embedding if it carries DEVICE evidence — every
# bench record (including device-less runs) has host-tier keys, so those
# must not qualify a candidate
_DEVICE_TIER_KEYS = (
    "feed_dense_mbps", "sgd_e2e_mbps", "sgd_e2e_cached_mbps",
    "sgd_csr_e2e_mbps", "recordio_sgd_mbps", "sgd_e2e_shard_mbps",
    "criteo_like_csr_sgd_mbps",
)


def _harvest_dirs():
    env = os.environ.get("DMLC_TPU_HARVEST_DIR")
    if env:
        yield env
    yield os.path.join(CACHE_DIR, "tpu_sweep")
    yield os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "artifacts", "tpu_sweep"
    )


def _json_lines(path):
    """Parsed JSON objects from a jsonl-ish file (missing/corrupt -> [])."""
    out = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line.startswith("{"):
                    out.append(json.loads(line))
    except (OSError, ValueError):
        pass
    return out


def _read_json_lines(path, want):
    """First JSON line in ``path`` for which ``want(obj)`` is truthy."""
    for obj in _json_lines(path):
        if want(obj):
            return obj
    return None


def _scan_harvest_dir(d):
    """One candidate dir → (has_device_tiers, timestamp, harvest dict) or
    None. Everything (selection score, timestamp, record) is captured in
    ONE pass so the chosen record and its provenance can't describe
    different files."""
    record = None
    mtime = None
    for name in ("bench_detail.json", "bench.json"):
        p = os.path.join(d, name)
        if not os.path.exists(p):
            continue
        cand = _read_json_lines(
            p, lambda o: "extra" in o or "feed_dense_mbps" in o)
        if cand is not None:
            record = cand.get("extra", cand)
            mtime = os.path.getmtime(p)
            break
    if record is None:
        return None
    out = {"provenance": "harvested", "dir": d}
    # measurement time comes from INSIDE the artifacts (summary.json's
    # "started"); file mtime is a fallback only and labeled as such —
    # a git checkout rewrites mtimes, so committed artifacts would
    # otherwise claim age ~0
    summary = _read_json_lines(
        os.path.join(d, "summary.json"), lambda o: "started" in o)
    if summary:
        out["harvested_at"] = summary["started"]
        try:
            ts = time.mktime(
                time.strptime(summary["started"], "%Y-%m-%d %H:%M:%S"))
            out["age_hours"] = round((time.time() - ts) / 3600, 1)
        except ValueError:
            pass
    else:
        out["harvested_at"] = time.strftime(
            "%Y-%m-%d %H:%M:%S", time.localtime(mtime))
        out["age_hours"] = round((time.time() - mtime) / 3600, 1)
        out["timestamp_source"] = "file-mtime (no summary.json)"
    for key in _COMPACT_KEYS:
        if key in record and not key.startswith(("socket_", "headline_")):
            out[key] = record[key]
    if isinstance(record.get("parity"), dict):
        out["parity"] = record["parity"]
    rows = [r for r in _json_lines(os.path.join(d, "pallas_flash.json"))
            if "T" in r]
    if rows:
        out["pallas_flash"] = rows
    # CPU-fallback records carry the same tier keys on the cpu backend;
    # only an actual accelerator run counts as harvest-worthy device
    # evidence (embedding cpu numbers as "harvested" would defeat the
    # provenance discipline). Detection must not hinge on the "device"
    # key alone — a TPU run whose feed tier errored doesn't set it but
    # its surviving SGD tiers are still real evidence — so fallback runs
    # are identified by their own markers: device=="cpu" or the
    # device_unavailable note both label the cpu path.
    cpu_fallback = (
        record.get("device") == "cpu" or "device_unavailable" in record
    )
    has_device = "pallas_flash" in out or (
        not cpu_fallback and any(k in out for k in _DEVICE_TIER_KEYS)
    )
    return has_device, out.get("age_hours", 1e9), out


def _load_latest_harvest():
    """Best available tpu_measure.py harvest → compact device-tier dict
    with provenance, or None. A dead tunnel at round end must not erase
    device numbers captured during a tunnel-up window earlier in the
    round — the harvest's own timestamp and age make the provenance
    explicit (these are NOT live numbers and are labeled so). Candidates
    WITH device tiers always outrank device-less records (a later failed
    sweep must not shadow an earlier good one); among equals, newest
    wins."""
    best = None  # (has_device, -age) ranking
    for d in _harvest_dirs():
        scanned = _scan_harvest_dir(d)
        if scanned is None:
            continue
        has_device, age, out = scanned
        rank = (1 if has_device else 0, -age)
        if best is None or rank > best[0]:
            best = (rank, out)
    if best is None or best[0][0] == 0:
        return None  # nothing with device evidence — embed nothing
    return best[1]


def _compact_summary(headline: float, extra: dict) -> dict:
    """The single stdout line: bounded (≤2 KB) so the driver's tail capture
    can never truncate it mid-JSON again (BENCH_r04 'parsed: null')."""
    compact = {}
    for key in _COMPACT_KEYS:
        if key in extra:
            compact[key] = extra[key]
    if isinstance(extra.get("parity"), dict):
        compact["parity"] = extra["parity"]
    probe = extra.get("device_probe", {}).get("attempts", [])
    compact["device_probe_ok"] = bool(probe) and probe[-1].get("ok", False)
    if isinstance(extra.get("sentry"), dict):
        compact["sentry_regressions"] = len(
            extra["sentry"].get("regressions", []))
    if "device_unavailable" in extra:
        compact["device_unavailable"] = extra["device_unavailable"][:120]
    for key, val in extra.items():
        if key.endswith("_error"):
            compact[key] = str(val)[:120]
    if "harvest" in extra:
        compact["harvest"] = extra["harvest"]
    if "detail_path" in extra:
        compact["detail_path"] = extra["detail_path"]
    line = {
        "metric": "higgs_libsvm_ingest",
        "value": round(headline, 1),
        "unit": "MB/s",
        "vs_baseline": round(headline / REFERENCE_MBPS, 3),
        "extra": compact,
    }
    # hard bound: shed payloads in increasing order of verdict value until
    # the line fits — first the bulky optionals, then error texts, then
    # non-tier context keys; the loop cannot exit oversize while anything
    # sheddable remains (the bare metric/value core is ~120 bytes)
    def _oversize():
        return len(json.dumps(line)) > 2048

    if _oversize() and isinstance(compact.get("harvest"), dict):
        compact["harvest"].pop("pallas_flash", None)
    for drop in ("harvest", "parity"):
        if _oversize():
            compact.pop(drop, None)
    if _oversize():
        for key in [k for k in compact if k.endswith("_error")]:
            compact.pop(key, None)
            if not _oversize():
                break
    if _oversize():
        for key in [k for k in compact
                    if k.startswith(("socket_", "headline_", "psum_",
                                     "bucket_", "engine_", "device_"))]:
            compact.pop(key, None)
            if not _oversize():
                break
    return line


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    t_run0 = time.time()
    path = _ensure_data()

    _one_pass(path, 1)  # warmup: native build, page cache, allocators

    # host tiers all follow the headline's bimodal-host discipline: three
    # sweeps spread across the run, probe next to each, best sweep scores
    host_tiers = {
        "recordio_ingest": lambda: _recordio_sweep(path),
        "criteo_like_parse": _criteo_parse_sweep,
        "parse_only": _parse_only_sweep,
        "criteo_recordio_ingest": _criteo_recordio_sweep,
        "shard_ingest": lambda: _shard_sweep(path),
        "remote_ingest": lambda: _remote_sweep(path),
    }
    tier_sweeps = {name: [] for name in host_tiers}

    def run_host_tier_sweeps():
        for name, fn in host_tiers.items():
            try:
                tier_sweeps[name].append(fn())
            except Exception as err:  # the headline must still print
                tier_sweeps[name].append({"error": str(err)})

    sweeps = [_headline_sweep(path)]
    run_host_tier_sweeps()  # tier sweep 1

    extra = {
        "criteo_like_file_mb": round(
            os.path.getsize(_ensure_criteo_like()) / (1 << 20), 1),
        "criteo_like_feature_space": CRITEO_DIM,
        "recordio_file_mb": round(
            os.path.getsize(_ensure_recordio(path)) / (1 << 20), 1),
        "criteo_recordio_file_mb": round(
            os.path.getsize(_ensure_criteo_recordio()) / (1 << 20), 1),
        "shard_file_mb": round(
            os.path.getsize(_ensure_shard(path)) / (1 << 20), 1),
    }
    device_ok, device_note, probe_record = _device_backend_ok()
    extra["device_probe"] = probe_record
    # host-speed context bracketing the device tiers (the probe itself is
    # not sweep-controlled like the tiers — r03→r04 it swung 1.12→0.71
    # with the documented host bimodality; a pre AND post reading makes a
    # slow window visible instead of letting it masquerade as a device
    # regression)
    extra["device_feed_probe_gbps"] = _host_probe()
    def _run_device_tiers():
        # each tier carries the host probe read just before it ran: the
        # device tiers share this host's core(s) with jax's runtime
        # threads, and trial spreads of 3-5x (r05 harvests: feed 67.9 vs
        # 241.2 in ONE tier) are host/tunnel-window noise — the per-tier
        # probe lets a reader attribute a slow tier to a slow window
        # instead of a regression
        tier_probes = {}
        for tier_fn, err_key in (
            (lambda: _bench_device_feed(path), "device_feed_error"),
            (lambda: _bench_recordio_sgd(path), "recordio_sgd_error"),
            (lambda: _bench_shard_sgd(path), "shard_sgd_error"),
            (_bench_criteo_sgd, "criteo_sgd_error"),
            (lambda: _bench_gbdt(path), "gbdt_error"),
            (lambda: _bench_multijob(path), "multijob_error"),
            (lambda: _bench_snapshot(path), "snapshot_error"),
        ):
            tier_probes[err_key.replace("_error", "_probe_gbps")] = (
                _host_probe()
            )
            try:
                extra.update(tier_fn())
            except Exception as err:
                extra[err_key] = str(err)
        extra["device_tier_probes_gbps"] = tier_probes
        try:
            # chip-vs-CPU-world parity artifact (north star: bit-exact
            # loss parity vs the CPU/MPI path; tools/parity.py documents
            # the reduction-order construction and what cross-backend
            # tolerance means)
            from dmlc_tpu.tools.parity import run_parity

            parity = run_parity(world=2, steps=3)
            extra["parity"] = {
                k: parity[k]
                for k in ("single_backend", "bitexact", "max_grad_ulp",
                          "max_loss_rel", "max_param_abs_diff",
                          "criterion", "pass")
            }
        except Exception as err:
            extra["parity_error"] = str(err)
        extra["device_feed_probe_gbps_post"] = _host_probe()

    if not device_ok:
        extra["device_unavailable"] = device_note + "; device tiers skipped"
        harvest = _load_latest_harvest()
        if harvest:
            extra["harvest"] = harvest
        # CPU-backend fallback: the ingest->SGD tiers are meaningful on
        # the CPU device and belong in the artifact (a dead tunnel must
        # not erase them). Forcing the platform BEFORE any backend init
        # is the one safe order — the tunneled plugin HANGS at init, and
        # env vars are overridden by the runtime's sitecustomize.
        try:
            import jax

            jax.config.update("jax_platforms", "cpu")
            _run_device_tiers()
            # amend only once the tiers actually ran — the message must
            # never claim measurements that don't exist
            extra["device_unavailable"] = device_note + (
                "; device tiers measured on the cpu backend"
            )
        except Exception as err:
            extra["device_cpu_fallback_error"] = str(err)
    else:
        _run_device_tiers()

    sweeps.append(_headline_sweep(path))
    run_host_tier_sweeps()  # tier sweep 2

    try:
        from bench_collective import collective_metrics

        extra.update(collective_metrics(device_ok=device_ok))
    except Exception as err:
        extra["collective_error"] = str(err)

    sweeps.append(_headline_sweep(path))
    run_host_tier_sweeps()  # tier sweep 3

    for name, tier in tier_sweeps.items():
        value, sw_extra = _combine_tier(tier)
        if value is None:
            extra[name + "_error"] = "; ".join(
                sw.get("error", "no trials") for sw in tier)
        else:
            extra[name + "_mbps"] = round(value, 1)
            extra[name + "_sweeps"] = sw_extra
    # per-(format, backend) parse-stage medians: best window across the
    # three parse_only sweeps, lifted to flat *_gbps keys so the sentry
    # gates each backend's parse throughput independently of the e2e tiers
    fmt_best: dict = {}
    for sw in tier_sweeps.get("parse_only", ()):
        for key, v in (sw.get("formats") or {}).items():
            if isinstance(v, (int, float)):
                fmt_best[key] = max(fmt_best.get(key, 0.0), float(v))
    for key, v in fmt_best.items():
        extra["parse_only_" + key] = v
    # the shard tier's headline is GB/s (the ISSUE's acceptance unit) and
    # the one-off bake cost rides inside its sweeps — lift both to flat
    # keys so the sentry gates them like any other throughput
    if "shard_ingest_mbps" in extra:
        extra["shard_ingest_gbps"] = round(
            extra.pop("shard_ingest_mbps") / 1024, 2)
    bake_best = [sw.get("bake_mbps") for sw in tier_sweeps.get(
        "shard_ingest", ()) if isinstance(sw.get("bake_mbps"), (int, float))]
    if bake_best:
        extra["bake_mbps"] = max(bake_best)
    if "remote_ingest_mbps" in extra:
        # The loopback harness runs BOTH http ends and the parser on this
        # host's core(s): at 1 core the serial budget is parse + server
        # slice/send + client recv, so ~55-70% of the local number IS the
        # all-on-one-core ceiling, not a product limit — the product path
        # (readahead fetch threads + native push parse) overlaps these on
        # independent cores/NICs on a real host.
        extra["remote_ingest_note"] = (
            "loopback fake-S3 shares this host's core(s) with the parser; "
            "serial floor, not the product ceiling"
        )

    headline, headline_extra = _combine_headline(sweeps)
    extra = {**headline_extra, **extra}

    try:
        # whole-run obs registry dump (per-stage histograms included);
        # detail-file only — too big for the compact stdout summary
        from dmlc_tpu import obs

        extra["metrics"] = obs.registry().snapshot()
    except Exception as err:
        extra["metrics_error"] = str(err)[:120]

    try:
        # device-side picture (compile counts, peak HBM, H2D MB/s) —
        # placed before the sentry pass so compiles.<fn>/hbm.peak_bytes/
        # h2d_mbps gate against history like any other metric
        from dmlc_tpu.obs import device_telemetry

        extra["device_telemetry"] = device_telemetry.detail_section()
    except Exception as err:
        extra["device_telemetry_error"] = str(err)[:120]

    try:
        # compiled-program cost records (obs/xla_cost.py): per-jit-site
        # flops / bytes accessed / peak memory / in-graph collective
        # bytes, cached at compile time by the instrumented_jit hook —
        # the SPMD psum step's dmlc_xla_collective_bytes lands here
        from dmlc_tpu.obs import xla_cost

        extra["xla"] = xla_cost.detail_section()
    except Exception as err:
        extra["xla_error"] = str(err)[:120]

    try:
        # whole-run goodput attribution (obs/goodput.py): the run's
        # registry totals ARE the delta-from-zero, the wall is this
        # process's elapsed time, and the ceilings are the run's OWN
        # measurements — parse_only tier for parse, the host H2D probe
        # for h2d — so the binding verdict rides the artifact and
        # sgd_goodput_ratio gates against history via the direction map
        from dmlc_tpu import obs
        from dmlc_tpu.obs import goodput as _goodput

        flat = obs.registry().flat_values()
        ceilings = _goodput.default_ceilings()
        probe = extra.get("device_feed_probe_gbps")
        if isinstance(probe, (int, float)) and probe > 0:
            ceilings["h2d_mbps"] = round(float(probe) * 1000.0, 1)
        parse_peak = max(
            (float(v) for k, v in extra.items()
             if k.startswith("parse_only_") and k.endswith("_gbps")
             and isinstance(v, (int, float))),
            default=0.0,
        )
        if parse_peak > 0:
            ceilings["parse_mbps"] = round(parse_peak * 1000.0, 1)
        att = _goodput.attribute(
            flat, max(time.time() - t_run0, 1e-9),
            ceilings=ceilings, current=flat,
        )
        extra["goodput"] = att
        extra["sgd_goodput_ratio"] = att["goodput"]["ratio"]
        if att.get("mfu") is not None:
            # model FLOP utilization rides the record only when the
            # run compiled an analyzable hot step — sentry gates it
            # higher-is-better via BENCH_DIRECTIONS
            extra["sgd_mfu"] = att["mfu"]
    except Exception as err:
        extra["goodput_error"] = str(err)[:120]

    try:
        # advisory perf-sentry pass (report-only — the blocking gate is
        # `dmlc_tpu.tools bench-gate` in scripts/ci_checks.sh): gate this
        # run against the committed round history so the regression
        # verdict rides the artifact itself
        import glob as _glob

        from dmlc_tpu.obs import sentry

        hist = sentry.load_records(sorted(_glob.glob(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_r*.json"))))
        if hist:
            fresh_rec = {"metric": "higgs_libsvm_ingest",
                         "value": round(headline, 1), "extra": extra,
                         "directions": dict(BENCH_DIRECTIONS)}
            regs = sentry.gate(
                sentry.record_values(fresh_rec),
                sentry.metric_series(hist),
                directions=sentry.record_directions(hist + [fresh_rec]),
            )
            extra["sentry"] = {
                "history_records": len(hist),
                "regressions": [
                    {k: r[k] for k in ("metric", "value", "baseline",
                                       "severity")} for r in regs[:5]
                ],
            }
    except Exception as err:
        extra["sentry_error"] = str(err)[:120]

    # full record to the detail file; COMPACT summary (≤2 KB) to stdout
    detail_path = os.environ.get(
        "DMLC_TPU_BENCH_DETAIL",
        os.path.join(CACHE_DIR, "bench_detail.json"),
    )
    detail_line = json.dumps(
        {
            "metric": "higgs_libsvm_ingest",
            "value": round(headline, 1),
            "unit": "MB/s",
            "vs_baseline": round(headline / REFERENCE_MBPS, 3),
            "extra": extra,
            # per-record sentry direction registry (obs/sentry.py):
            # names extra keys the gate scores beyond the suffix rules
            "directions": dict(BENCH_DIRECTIONS),
        }
    )
    try:
        os.makedirs(os.path.dirname(detail_path) or ".", exist_ok=True)
        with open(detail_path, "w") as fh:
            fh.write(detail_line + "\n")
        extra["detail_path"] = detail_path
    except OSError as err:  # detail is best-effort; the summary must print
        extra["detail_write_error"] = str(err)[:120]

    print(json.dumps(_compact_summary(headline, extra)))


if __name__ == "__main__":
    main()
