#!/usr/bin/env python
"""Distributed allreduce-SGD on LibSVM data — the BASELINE north-star loop.

Runs under any launcher that exports the DMLC_* env contract::

    ./dmlc-submit --cluster=local -n 4 python examples/distributed_sgd.py data.svm
    ./dmlc-submit --cluster=ssh -H hosts.txt -n 8 python examples/distributed_sgd.py gs://b/data.svm
    ./dmlc-submit --cluster=tpu --tpu-name v5e -n 16 python examples/distributed_sgd.py ...

or standalone (world size 1)::

    python examples/distributed_sgd.py data.svm [--epochs N]

Each worker reads its own InputSplit part (part=rank of world), computes a
local logistic-regression gradient per block, allreduces it (socket tree on
CPU clusters, psum over ICI under --cluster=tpu), and steps. Checkpoints go
through the rabit-style ``checkpoint``/``load_checkpoint`` so a restarted
worker resumes at the last committed epoch.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from dmlc_tpu import collective as rabit
from dmlc_tpu.data import create_parser


def sigmoid(x):
    return 0.5 * (1.0 + np.tanh(0.5 * x))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("uri")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--num-features", type=int, default=0,
                    help="0 = discover from the data (epoch-0 max index + 1)")
    ap.add_argument("--checkpoint-uri", default="")
    ap.add_argument("--shuffle", type=int, default=None, metavar="SEED",
                    help="visit each epoch's chunks in seeded random "
                         "order (?shuffle_chunks=SEED+epoch: fresh "
                         "permutation per epoch, replayable from SEED)")
    args = ap.parse_args()

    def epoch_uri(epoch: int) -> str:
        if args.shuffle is None:
            return args.uri
        sep = "&" if "?" in args.uri else "?"
        return f"{args.uri}{sep}shuffle_chunks={args.shuffle + epoch}"

    rabit.init()
    rank, world = rabit.rank(), rabit.world_size()

    start_epoch = 0
    ckpt = rabit.load_checkpoint(args.checkpoint_uri or None)
    if ckpt is not None:
        # the checkpoint fixes w (and therefore the feature-space width):
        # skip the discovery pass entirely on resume
        start_epoch, w = ckpt
        if rank == 0:
            rabit.tracker_print(f"resumed at epoch {start_epoch}")
    else:
        # discover the feature-space width across all parts
        num_features = args.num_features
        if num_features == 0:
            parser = create_parser(args.uri, rank, world)
            local_max = 0
            for block in parser:
                if len(block.index):
                    local_max = max(local_max, int(block.index.max()))
            parser.close()
            num_features = int(
                rabit.allreduce(np.array([local_max], np.int64), op="max")[0]
            ) + 1
        w = np.zeros(num_features + 1, dtype=np.float64)  # [weights..., bias]

    for epoch in range(start_epoch, args.epochs):
        parser = create_parser(epoch_uri(epoch), rank, world)
        grad = np.zeros_like(w)
        loss = 0.0
        weight_sum = 0.0
        for block in parser:
            # CSR block -> dense margin via segment sums (numpy reference
            # loop; models/linear.py holds the jitted TPU twin)
            n = len(block)
            vals = (block.value if block.value is not None
                    else np.ones_like(block.index, np.float32))
            row_ids = np.repeat(np.arange(n), np.diff(block.offset))
            margins = np.bincount(
                row_ids, weights=vals * w[block.index], minlength=n
            ) + w[-1]
            y = (block.label > 0).astype(np.float64)
            p = sigmoid(margins)
            g = p - y
            np.add.at(grad[:-1], block.index, g[row_ids] * vals)
            grad[-1] += g.sum()
            loss += float(
                np.sum(np.maximum(margins, 0) - margins * y
                       + np.log1p(np.exp(-np.abs(margins))))
            )
            weight_sum += len(block)
        parser.close()

        # grad sync: one fused allreduce over [grad, loss, count]
        packed = np.concatenate([grad, [loss, weight_sum]])
        packed = rabit.allreduce(packed, op="sum")
        grad, loss, weight_sum = packed[:-2], packed[-2], packed[-1]
        denom = max(weight_sum, 1e-12)
        w -= args.lr * grad / denom
        if rank == 0:
            rabit.tracker_print(
                f"epoch {epoch}: loss={loss / denom:.6f} "
                f"examples={int(weight_sum)}"
            )
        # only rank 0 persists to the shared URI (w is identical on every
        # rank after the allreduce; concurrent writers would tear the file)
        rabit.checkpoint(
            (epoch + 1, w),
            (args.checkpoint_uri or None) if rank == 0 else None,
        )

    rabit.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
