#!/usr/bin/env python
"""Criteo-class sparse training end to end — the north-star workload.

High-cardinality hashed features (2^20 id space, ~39 nnz/row) through the
sparse device path::

    python examples/criteo_sparse.py data.svm --num-features 1048577
    python examples/criteo_sparse.py --synthetic        # self-contained demo
    python examples/criteo_sparse.py data.rec --format recordio  # binary shards

The pipeline this demonstrates (every stage measured in bench.py):

1. parse — text LibSVM or (recommended for steady state: 5x the MB/s,
   ~40% smaller files) binary row-group RecordIO shards
   (``dmlc_tpu.tools rowrec`` converts);
2. ``DeviceFeed(spec, layout="csr")`` — static-shape COO batches: values/
   indices padded to ``nnz_bucket`` (no recompilation storms, SURVEY §7),
   row ids shipped as CSR offsets (4 B/row instead of 4 B/entry across
   H2D) and expanded on device;
3. ``make_linear_train_step(layout="csr")`` — segment-sum SpMV forward
   and scatter-add gradient (the TPU-native Row::SDot), one fused psum
   under a mesh, batch buffers donated;
4. on a multi-chip mesh the feed ships a ``ShardedCSRBatch``: each device
   receives ONLY its shard's entries (per-device H2D ∝ global_nnz/world —
   the Criteo-1TB scale contract).

Single-process; for the multi-host launch story see
``examples/distributed_sgd.py`` (this example is about the sparse device
path, that one about the launch/collective contract).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _synthesize(path: str, rows: int = 20_000, dim: int = 1 << 20,
                nnz: int = 39) -> None:
    # write-to-.tmp + atomic replace: an interrupted run must not leave a
    # truncated file that later runs silently reuse
    rng = np.random.RandomState(7)
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        for start in range(0, rows, 5000):
            n = min(5000, rows - start)
            labels = rng.randint(0, 2, size=n)
            ids = rng.randint(0, dim, size=(n, nnz))
            ids.sort(axis=1)
            vals = rng.rand(n, nnz)
            fh.write("\n".join(
                str(labels[i]) + " " + " ".join(
                    f"{ids[i, j]}:{vals[i, j]:.4f}" for j in range(nnz))
                for i in range(n)) + "\n")
    os.replace(tmp, path)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("uri", nargs="?", default=None)
    ap.add_argument("--synthetic", action="store_true",
                    help="generate a small criteo-shaped file and train on it")
    ap.add_argument("--format", default="auto",
                    choices=["auto", "libsvm", "recordio"])
    ap.add_argument("--num-features", type=int, default=(1 << 20) + 1)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=8192)
    ap.add_argument("--nnz-bucket", type=int, default=1 << 19)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()
    if args.uri is None and not args.synthetic:
        ap.error("give a data URI or --synthetic")

    import jax

    # honor an explicit JAX_PLATFORMS even when a site hook pre-imported
    # jax with another platform (same idiom as the other jax examples)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)

    import jax.numpy as jnp

    from dmlc_tpu.data import create_parser
    from dmlc_tpu.device import BatchSpec, DeviceFeed
    from dmlc_tpu.models.linear import (
        EpochMetrics,
        init_linear_params,
        make_linear_train_step,
        step_batch,
    )

    uri = args.uri
    if args.synthetic:
        import tempfile

        uri = os.path.join(tempfile.gettempdir(), "criteo_sparse_demo.svm")
        if not os.path.exists(uri):
            _synthesize(uri)
        print(f"synthetic criteo-shaped data at {uri}")

    spec = BatchSpec(batch_size=args.batch_size, layout="csr",
                     num_features=args.num_features,
                     nnz_bucket=args.nnz_bucket)
    step = make_linear_train_step(
        None, learning_rate=args.lr, layout="csr",
        num_features=args.num_features, donate_batch=True,
    )
    params = init_linear_params(args.num_features)
    velocity = {k: jnp.zeros_like(v) for k, v in params.items()}

    size_mb = None
    if "://" not in (uri or "") and os.path.exists(uri):
        size_mb = os.path.getsize(uri) / (1 << 20)
    for epoch in range(args.epochs):
        feed = DeviceFeed(
            create_parser(uri, 0, 1, data_format=args.format), spec)
        acc = EpochMetrics()
        t0 = time.time()
        nstep = 0
        for batch in feed:
            params, velocity, metrics = step(
                params, velocity, step_batch(batch, "csr"))
            acc.add(metrics)
            nstep += 1
        dt = time.time() - t0
        feed.close()
        rate = f", {size_mb / dt:.0f} MB/s" if size_mb else ""
        print(f"epoch {epoch}: loss {acc.mean_loss():.6f} "
              f"({nstep} steps, {dt:.2f}s{rate})")
    nnz_w = int(jnp.sum(params["w"] != 0))
    print(f"done: {nnz_w} touched weights of {args.num_features}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
