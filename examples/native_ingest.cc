// Consuming libdmlc_tpu.so from C++ — the analog of linking the
// reference's libdmlc.a (example/: parameter.cc is its demo; this is ours
// for the native ingest core).
//
// Build (from the repo root, after `make -C cpp`; one line):
//   g++ -O2 -std=c++17 examples/native_ingest.cc
//       -Icpp -Lcpp -ldmlc_tpu -Wl,-rpath,$PWD/cpp -o native_ingest
//   ./native_ingest data.svm
//
// Streams a libsvm file through the threaded native pipeline (reader
// thread -> parse workers -> ordered CSR blocks) and prints totals — the
// same engine the Python package drives through ctypes.

#include <cinttypes>
#include <cstdio>
#include <cstring>
#include <string>
#include <sys/stat.h>

#include "dmlc_tpu.h"

int main(int argc, char** argv) {
  if (argc != 2) {
    std::fprintf(stderr, "usage: %s <file.svm>\n", argv[0]);
    return 2;
  }
  if (dmlc_tpu_abi_version() != DMLC_TPU_ABI_VERSION) {
    std::fprintf(stderr, "ABI mismatch: header %d, library %d\n",
                 DMLC_TPU_ABI_VERSION, dmlc_tpu_abi_version());
    return 2;
  }
  struct stat st;
  if (stat(argv[1], &st) != 0) {
    std::perror("stat");
    return 1;
  }
  // paths: NUL-terminated strings back to back (one file here)
  std::string paths(argv[1]);
  paths.push_back('\0');
  int64_t size = static_cast<int64_t>(st.st_size);
  void* h = ingest_open(paths.data(), &size, /*nfiles=*/1,
                        DMLC_TPU_FORMAT_LIBSVM, /*part=*/0, /*nparts=*/1,
                        /*nthread=*/2, /*chunk_bytes=*/8 << 20,
                        /*capacity=*/4, /*csv_expect_cols=*/0);
  if (h == nullptr) {
    std::fprintf(stderr, "ingest_open failed\n");
    return 1;
  }
  int64_t total_rows = 0, total_nnz = 0, blocks = 0;
  for (;;) {
    int64_t rows, nnz, ncols;
    int32_t flags;
    int rc = ingest_peek(h, &rows, &nnz, &ncols, &flags);
    if (rc == 0) break;  // end of stream
    if (rc < 0) {
      std::fprintf(stderr, "pipeline error rc=%d\n", rc);
      ingest_close(h);
      return 1;
    }
    float *labels, *weights, *values;
    int64_t *qids, *offsets;
    uint32_t *indices, *fields;
    void* block = ingest_fetch_view(h, &labels, &weights, &qids, &offsets,
                                    &indices, &values, &fields);
    // zero-copy CSR views are valid until ingest_block_free
    total_rows += rows;
    total_nnz += offsets[rows];
    ++blocks;
    ingest_block_free(block);
  }
  std::printf("rows=%" PRId64 " nnz=%" PRId64 " blocks=%" PRId64
              " bytes=%" PRId64 "\n",
              total_rows, total_nnz, blocks, ingest_bytes_read(h));
  ingest_close(h);
  return 0;
}
