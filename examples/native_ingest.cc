// Consuming libdmlc_tpu.so from C++ — the analog of linking the
// reference's libdmlc.a (example/: parameter.cc is its demo; this is ours
// for the native ingest core).
//
// Build (from the repo root, after `make -C cpp`; one line):
//   g++ -O2 -std=c++17 -pthread examples/native_ingest.cc
//       -Icpp -Lcpp -ldmlc_tpu -Wl,-rpath,$PWD/cpp -o native_ingest
//   ./native_ingest data.svm            # local-file reader pipeline
//   ./native_ingest --remote data.svm   # remote-shaped drive_push path
//
// Default mode streams a libsvm file through the threaded native pipeline
// (reader thread -> parse workers -> ordered CSR blocks) and prints
// totals — the same engine the Python package drives through ctypes.
//
// --remote demonstrates ingest_drive_push, the C-consumer remote-ingest
// surface: the pipeline ships no transport (the consumer brings libcurl /
// an SDK / a socket — here a pread-backed callback stands in for ranged
// GETs), and the fetch callback lands bytes directly in pipeline push
// memory (readinto semantics, no staging copy). The driver blocks for
// backpressure, so real consumers run it on a feeder thread while the
// main thread drains — exactly what this program does.

#include <cinttypes>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <sys/stat.h>

#include "dmlc_tpu.h"

namespace {

// The stand-in "transport": serve [offset, offset+len) of a local file the
// way a ranged-GET loop would. A real consumer points this at HTTP.
int64_t FileFetch(void* ctx, int64_t offset, char* buf, int64_t len) {
  std::FILE* f = static_cast<std::FILE*>(ctx);
  if (std::fseek(f, static_cast<long>(offset), SEEK_SET) != 0) return -1;
  size_t got = std::fread(buf, 1, static_cast<size_t>(len), f);
  if (got == 0 && std::ferror(f)) return -1;
  return static_cast<int64_t>(got);
}

}  // namespace

int main(int argc, char** argv) {
  bool remote = argc == 3 && std::strcmp(argv[1], "--remote") == 0;
  if (argc != 2 && !remote) {
    std::fprintf(stderr, "usage: %s [--remote] <file.svm>\n", argv[0]);
    return 2;
  }
  if (remote) argv[1] = argv[2];
  if (dmlc_tpu_abi_version() != DMLC_TPU_ABI_VERSION) {
    std::fprintf(stderr, "ABI mismatch: header %d, library %d\n",
                 DMLC_TPU_ABI_VERSION, dmlc_tpu_abi_version());
    return 2;
  }
  struct stat st;
  if (stat(argv[1], &st) != 0) {
    std::perror("stat");
    return 1;
  }
  int64_t size = static_cast<int64_t>(st.st_size);
  void* h;
  std::thread feeder;
  std::FILE* remote_file = nullptr;
  if (remote) {
    h = ingest_open_push(DMLC_TPU_FORMAT_LIBSVM, /*nthread=*/2,
                         /*chunk_bytes=*/8 << 20, /*capacity=*/4,
                         /*csv_expect_cols=*/0);
    if (h == nullptr) {
      std::fprintf(stderr, "ingest_open_push failed\n");
      return 1;
    }
    remote_file = std::fopen(argv[1], "rb");
    if (remote_file == nullptr) {
      std::perror("fopen");
      ingest_close(h);
      return 1;
    }
    feeder = std::thread([h, remote_file, size] {
      int rc = ingest_drive_push(h, FileFetch, remote_file, size,
                                 /*fetch_bytes=*/1 << 20);
      if (rc != 0) std::fprintf(stderr, "drive_push rc=%d\n", rc);
    });
  } else {
    // paths: NUL-terminated strings back to back (one file here)
    std::string paths(argv[1]);
    paths.push_back('\0');
    h = ingest_open(paths.data(), &size, /*nfiles=*/1,
                    DMLC_TPU_FORMAT_LIBSVM, /*part=*/0, /*nparts=*/1,
                    /*nthread=*/2, /*chunk_bytes=*/8 << 20,
                    /*capacity=*/4, /*csv_expect_cols=*/0);
    if (h == nullptr) {
      std::fprintf(stderr, "ingest_open failed\n");
      return 1;
    }
  }
  int64_t total_rows = 0, total_nnz = 0, blocks = 0;
  for (;;) {
    int64_t rows, nnz, ncols;
    int32_t flags;
    int rc = ingest_peek(h, &rows, &nnz, &ncols, &flags);
    if (rc == 0) break;  // end of stream
    if (rc < 0) {
      std::fprintf(stderr, "pipeline error rc=%d\n", rc);
      if (feeder.joinable()) feeder.join();
      if (remote_file != nullptr) std::fclose(remote_file);
      ingest_close(h);
      return 1;
    }
    float *labels, *weights, *values;
    int64_t *qids, *offsets;
    uint32_t *indices, *fields;
    void* block = ingest_fetch_view(h, &labels, &weights, &qids, &offsets,
                                    &indices, &values, &fields);
    // zero-copy CSR views are valid until ingest_block_free
    total_rows += rows;
    total_nnz += offsets[rows];
    ++blocks;
    ingest_block_free(block);
  }
  std::printf("rows=%" PRId64 " nnz=%" PRId64 " blocks=%" PRId64
              " bytes=%" PRId64 "\n",
              total_rows, total_nnz, blocks, ingest_bytes_read(h));
  if (feeder.joinable()) feeder.join();
  if (remote_file != nullptr) std::fclose(remote_file);
  ingest_close(h);
  return 0;
}
