"""A sharded MoE-transformer block from this framework's parallel layers.

Composes, on ONE 2-D mesh (dp x sp over whatever devices exist):

- causal RING attention with GQA (seq sharded over sp, batch over dp),
- a switch-MoE FFN (experts sharded over the same sp axis — one axis can
  serve both schedules; tokens ride the identical sharding),
- residual connections and RMSNorm,

and checks the whole block, end to end, against a single-device reference
built from ``full_attention`` + ``moe_dense_oracle``. This is the
composition story: the parallel layers are factories over a shared mesh,
so a model is just Python composition plus one sharding annotation per
tensor (the scaling-book recipe).

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
    JAX_PLATFORMS=cpu python examples/moe_transformer.py
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--d-model", type=int, default=32)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--kv-heads", type=int, default=2)
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--top-k", type=int, default=2,
                    help="experts per token (1=switch, 2=GShard)")
    args = ap.parse_args()

    import jax

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from dmlc_tpu.ops import (
        full_attention,
        init_moe_params,
        make_moe_layer,
        make_ring_attention,
        moe_dense_oracle,
        shard_moe_params,
    )

    devices = np.asarray(jax.devices())
    n = len(devices)
    if n < 4 or n % 2:
        print(f"need an even device count >= 4, have {n}", file=sys.stderr)
        return 2
    mesh = Mesh(devices.reshape(2, n // 2), ("dp", "sp"))
    sp = mesh.shape["sp"]
    print(f"mesh: dp=2 x sp={sp} ({devices[0].platform})")

    d, h, hk = args.d_model, args.heads, args.kv_heads
    hd = d // h
    t = args.seq - args.seq % (2 * sp)
    b = 2
    e = args.experts - args.experts % sp
    if t <= 0 or e <= 0:
        print(f"--seq {args.seq} / --experts {args.experts} too small for "
              f"sp={sp} (need seq >= {2 * sp}, experts >= {sp})",
              file=sys.stderr)
        return 2

    rng = np.random.RandomState(0)
    params = {
        "wq": jnp.asarray(rng.randn(d, h * hd).astype(np.float32) / np.sqrt(d)),
        "wk": jnp.asarray(rng.randn(d, hk * hd).astype(np.float32) / np.sqrt(d)),
        "wv": jnp.asarray(rng.randn(d, hk * hd).astype(np.float32) / np.sqrt(d)),
        "wo": jnp.asarray(rng.randn(h * hd, d).astype(np.float32) / np.sqrt(d)),
        "moe": init_moe_params(e, d, 4 * d, seed=1),
    }
    x = jnp.asarray(rng.randn(b, t, d).astype(np.float32))

    def rmsnorm(v):
        return v * jax.lax.rsqrt(jnp.mean(v * v, axis=-1, keepdims=True) + 1e-6)

    ring = make_ring_attention(mesh, causal=True, axis="sp", batch_axis="dp")
    # capacity per (device, expert) against LOCAL tokens: top-k expert ids
    # are DISTINCT per token, so an expert receives at most one claim per
    # token — t//sp (= local tokens) is the tight no-drop bound for ANY k
    moe = make_moe_layer(mesh, e, capacity=t // sp, axis="sp",
                         batch_axis="dp", top_k=args.top_k)

    def qkv(v):
        vn = rmsnorm(v)
        q = (vn @ params["wq"]).reshape(b, t, h, hd)
        k = (vn @ params["wk"]).reshape(b, t, hk, hd)
        vv = (vn @ params["wv"]).reshape(b, t, hk, hd)
        return q, k, vv

    # ---- sharded block on the mesh --------------------------------------
    spec = NamedSharding(mesh, P("dp", "sp"))
    xs = jax.device_put(x, spec)
    q, k, v = qkv(xs)
    attn = jnp.asarray(
        ring(jax.device_put(q, spec), jax.device_put(k, spec),
             jax.device_put(v, spec))
    ).reshape(b, t, h * hd)
    y1 = xs + attn @ params["wo"]
    moe_params = shard_moe_params(params["moe"], mesh, axis="sp")
    ffn, aux = moe(moe_params, jax.device_put(rmsnorm(y1), spec))
    y_sharded = np.asarray(y1 + jnp.asarray(ffn))

    # ---- single-device reference ----------------------------------------
    q, k, v = qkv(x)
    attn_ref = full_attention(q, k, v, causal=True).reshape(b, t, h * hd)
    y1_ref = x + attn_ref @ params["wo"]
    ffn_ref, _ = moe_dense_oracle(params["moe"], rmsnorm(y1_ref),
                                  top_k=args.top_k)
    y_ref = np.asarray(y1_ref + ffn_ref)

    err = float(np.max(np.abs(y_sharded - y_ref)))
    print(f"block: ring-attn(GQA {h}q/{hk}kv, causal) + "
          f"MoE(E={e}, top-{args.top_k}) + residuals/RMSNorm over T={t}")
    print(f"max|Δ| sharded vs single-device = {err:.2e} "
          f"(aux={float(aux):.3f})")
    ok = err < 1e-3
    print("block matches the single-device reference" if ok else "MISMATCH")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
