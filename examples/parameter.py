#!/usr/bin/env python
"""Parameter module example (the reference's example/parameter.cc).

Usage::

    python examples/parameter.py num_hidden=100 name=aaa activation=relu

Run with no arguments to see the auto-generated docstring; pass a bad value
(activation=tanh, num_hidden=-1) to see constraint errors.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_tpu.params import ParamError, Parameter, field


class MyParam(Parameter):
    num_hidden = field(
        int, lower_bound=0, upper_bound=1000,
        description="Number of hidden units in the fully connected layer.",
        aliases=["nhidden"],  # user can also set nhidden=...
    )
    learning_rate = field(
        float, 0.01, description="Learning rate of SGD optimization."
    )
    activation = field(
        int, enum={"relu": 1, "sigmoid": 2},
        description="Activation function type.", aliases=["act"],
    )
    name = field(str, "mnet", description="Name of the net.")


def main(argv):
    if not argv:
        print("Usage: parameter.py key=value ...")
        print("example: parameter.py num_hidden=100 name=aaa activation=relu")
        print()
        print("parameters:")
        print(MyParam.__doc_string__())
        return 1
    kwargs = dict(kv.split("=", 1) for kv in argv)
    param = MyParam()
    try:
        param.init(kwargs)
    except ParamError as err:
        print(f"error: {err}", file=sys.stderr)
        return 1
    print(f"param.num_hidden={param.num_hidden}")
    print(f"param.learning_rate={param.learning_rate}")
    print(f"param.activation={param.activation}")
    print(f"param.name={param.name}")
    print(f"as dict: {param.to_dict()}")
    print(f"as json: {param.saves()}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
