#!/usr/bin/env python
"""Histogram gradient-boosted trees — the xgboost-over-rabit workload.

The reference backbone's whole purpose was feeding RowBlocks to xgboost and
allreducing its gradient histograms through rabit's socket tree (reference
tracker/dmlc_tracker/tracker.py:185-252). This example runs that workload
on the rebuilt stack end to end::

    python examples/boosted_trees.py data.svm --num-features 29
    python examples/boosted_trees.py --synthetic          # self-contained
    python examples/boosted_trees.py --synthetic --dp 8   # mesh histogram psum

Pipeline:

1. ingest — any parser uri (LibSVM text, binary RecordIO row groups,
   ``#cachefile``, object-store) materialized to a dense matrix: GBDT's
   hist mode is an in-core epoch-free algorithm (xgboost's default), so
   ingest happens once, not per epoch;
2. quantile binning on device (``fit_bins``/``apply_bins``) — training
   never touches floats again;
3. level-wise tree growth: per-level (grad, hess) histograms by
   segment-sum; under ``--dp N`` the samples are sharded over an N-way
   mesh axis and ONE psum per level syncs histograms across ICI — rabit's
   allreduce as an XLA collective;
4. vectorized split finding + leaf values (cumsum/argmax, no
   data-dependent control flow — the whole tree build jits once).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _load_dense(uri: str, num_features: int, part: int, nparts: int):
    """Materialize a parser uri into dense x [N, F], y [N] (hist mode is
    in-core: one pass, BasicRowIter-style — basic_row_iter.h:61-82)."""
    from dmlc_tpu.data import create_parser

    xs, ys = [], []
    parser = create_parser(uri, part, nparts)
    for block in parser:
        xs.append(block.to_dense(num_features))
        ys.append(np.asarray(block.label, dtype=np.float32))
    parser.close()
    return np.concatenate(xs), np.concatenate(ys)


def _synthetic_multiclass(k: int, n: int = 8192, f: int = 12):
    rng = np.random.RandomState(19)
    x = rng.rand(n, f).astype(np.float32)
    y = np.minimum(
        (x[:, 0] > 0.5) * 2 + (x[:, 1] > 0.5), k - 1
    ).astype(np.float32)
    flip = rng.rand(n) < 0.04
    y[flip] = rng.randint(0, k, int(flip.sum()))
    return x, y


def _synthetic(n: int = 8192, f: int = 16):
    rng = np.random.RandomState(11)
    x = rng.rand(n, f).astype(np.float32)
    logit = (
        5.0 * (x[:, 0] > 0.6)
        - 4.0 * ((x[:, 1] > 0.25) & (x[:, 2] < 0.75))
        + 2.0 * x[:, 3]
        - 1.0
    )
    y = (rng.rand(n) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float32)
    return x, y


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("uri", nargs="?", help="training data uri (any parser)")
    ap.add_argument("--synthetic", action="store_true")
    ap.add_argument("--num-features", type=int, default=0)
    ap.add_argument("--num-trees", type=int, default=20)
    ap.add_argument("--max-depth", type=int, default=5)
    ap.add_argument("--learning-rate", type=float, default=0.4)
    ap.add_argument("--num-bins", type=int, default=64)
    ap.add_argument("--objective", default="logistic",
                    choices=("logistic", "squared", "softmax"))
    ap.add_argument("--num-class", type=int, default=0,
                    help="class count for --objective softmax (labels "
                         "are class ids); --synthetic then generates a "
                         "4-class problem")
    ap.add_argument("--dp", type=int, default=0,
                    help="shard samples over a dp-way mesh axis "
                         "(histograms cross the mesh in one psum/level)")
    ap.add_argument("--save", help="checkpoint uri (any Stream backend)")
    args = ap.parse_args()

    import jax

    # honor an explicit JAX_PLATFORMS even when a site hook pre-imported
    # jax with another platform (same idiom as the other jax examples)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)

    from dmlc_tpu.models.gbdt import GBDTLearner

    mesh = None
    if args.dp:
        from dmlc_tpu.parallel import make_mesh

        mesh = make_mesh({"dp": args.dp})

    softmax = args.objective == "softmax"
    if softmax and args.num_class < 2:
        # default only where we control the data: a real uri's class
        # count is the user's to declare (guessing trains a wrong-width
        # model or dies on the label-range check)
        if args.uri and not args.synthetic:
            ap.error("--objective softmax with a data uri requires "
                     "--num-class")
        args.num_class = 4  # the synthetic multiclass default
    learner = GBDTLearner(
        mesh=mesh,
        objective=args.objective,
        num_class=args.num_class,
        num_trees=args.num_trees,
        max_depth=args.max_depth,
        learning_rate=args.learning_rate,
        num_bins=args.num_bins,
    )
    log_every = max(1, args.num_trees // 5)
    t0 = time.time()
    if args.synthetic or not args.uri:
        x, y = _synthetic_multiclass(args.num_class) if softmax \
            else _synthetic()
        if mesh:
            n = (x.shape[0] // args.dp) * args.dp
            x, y = x[:n], y[:n]
        history = learner.fit(x, y, log_every=log_every)
        dt = time.time() - t0
    else:
        if args.num_features <= 0:
            ap.error("--num-features is required with a data uri")
        # the streaming path: reservoir-sketch edges, bin block by block —
        # the dense float matrix never materializes during training
        # (hist external-memory); under --dp the tail rows that don't
        # divide the mesh are trimmed, matching the synthetic branch
        history = learner.fit_uri(args.uri, args.num_features,
                                  log_every=log_every,
                                  drop_remainder=bool(mesh))
        dt = time.time() - t0  # fit only — the eval reload isn't training
        x, y = _load_dense(args.uri, args.num_features, 0, 1)
    prob = learner.predict(x)
    acc = float(np.mean(prob.argmax(axis=1) == y)) if softmax \
        else float(np.mean((prob > 0.5) == (y > 0.5)))
    print(
        f"trees={args.num_trees} depth={args.max_depth} "
        f"rows={x.shape[0]} loss {history[0]:.4f} -> {history[-1]:.4f} "
        f"train-acc {acc:.4f} fit {dt:.2f}s"
        + (f" (dp={args.dp} histogram psum)" if mesh else "")
    )
    if args.save:
        learner.save(args.save)
        print(f"saved -> {args.save}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
