"""Long-context attention over a sequence-parallel mesh — runnable demo.

The reference predates long-context training (SURVEY §5.7); this framework
ships the standard schedules TPU-first (docs/distributed.md). This demo
runs all of them on whatever devices exist (a TPU slice, or a virtual CPU
mesh via XLA_FLAGS=--xla_force_host_platform_device_count=8) and checks
each against exact full attention:

    python examples/long_context.py [--seq 512] [--heads 8] [--kv-heads 2]

Schedules shown: ring (contiguous + zigzag layouts, causal, sliding
window) and Ulysses all-to-all; grouped-query attention throughout.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--kv-heads", type=int, default=2)
    ap.add_argument("--head-dim", type=int, default=32)
    ap.add_argument("--window", type=int, default=64)
    args = ap.parse_args()

    import jax

    # honor an explicit JAX_PLATFORMS even when a site hook pre-imported
    # jax with its own platform pick (config wins pre-backend-creation)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from dmlc_tpu.ops import (
        full_attention,
        make_ring_attention,
        make_ulysses_attention,
        zigzag_shard,
        zigzag_unshard,
    )

    devices = np.asarray(jax.devices())
    n = len(devices)
    mesh = Mesh(devices, ("sp",))
    print(f"mesh: {n} x {devices[0].platform} over axis 'sp'")

    t = args.seq - args.seq % (2 * n)  # zigzag needs T % 2N == 0
    if t <= 0:
        print(f"--seq {args.seq} is smaller than 2*num_devices ({2 * n}); "
              f"need at least one sequence chunk per device pair",
              file=sys.stderr)
        return 2
    rng = np.random.RandomState(0)
    q = jnp.asarray(
        rng.randn(1, t, args.heads, args.head_dim).astype(np.float32))
    k = jnp.asarray(
        rng.randn(1, t, args.kv_heads, args.head_dim).astype(np.float32))
    v = jnp.asarray(
        rng.randn(1, t, args.kv_heads, args.head_dim).astype(np.float32))
    print(f"shapes: q[1,{t},{args.heads},{args.head_dim}] "
          f"kv[1,{t},{args.kv_heads},{args.head_dim}] (GQA ratio "
          f"{args.heads // args.kv_heads})")

    def shard(x):
        return jax.device_put(x, NamedSharding(mesh, P(None, "sp")))

    def report(name, got, want):
        err = float(jnp.max(jnp.abs(got - want)))
        ok = err < 1e-3
        print(f"  {name:<42} max|Δ| vs exact = {err:.2e} "
              f"{'ok' if ok else 'MISMATCH'}")
        return ok

    ok = True

    want = full_attention(q, k, v, causal=True)
    ring = make_ring_attention(mesh, causal=True)
    got = ring(shard(q), shard(k), shard(v))
    ok &= report("ring, contiguous, causal", jnp.asarray(got), want)

    ring_zz = make_ring_attention(mesh, causal=True, layout="zigzag")
    got = zigzag_unshard(
        jnp.asarray(ring_zz(shard(zigzag_shard(q, n)),
                            shard(zigzag_shard(k, n)),
                            shard(zigzag_shard(v, n)))), n)
    ok &= report("ring, zigzag (load-balanced), causal", got, want)

    want_w = full_attention(q, k, v, window=args.window)
    ring_w = make_ring_attention(mesh, window=args.window)
    got = ring_w(shard(q), shard(k), shard(v))
    ok &= report(f"ring, sliding window W={args.window}",
                 jnp.asarray(got), want_w)

    if args.heads % n == 0 and args.kv_heads % n == 0:
        want_u = full_attention(q, k, v)
        ulysses = make_ulysses_attention(mesh)
        got = ulysses(shard(q), shard(k), shard(v))
        ok &= report("ulysses all-to-all", jnp.asarray(got), want_u)
    else:
        print(f"  ulysses skipped (heads {args.heads}/{args.kv_heads} do "
              f"not divide over {n} devices)")

    print("all schedules match exact attention" if ok else "MISMATCH")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
