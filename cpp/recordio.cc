// Native RecordIO framing: pack/unpack hot loops.
//
// TPU-build equivalent of the reference's RecordIO core (src/recordio.cc:
// WriteRecord 11-51, NextRecord 53-82, ChunkReader 101-156): the per-record
// frame/scan/reassemble loops live in C++ behind the same flat C ABI as
// parse.cc. Batch-oriented by design — the Python side hands a whole chunk
// (or a batch of records) across ctypes once, instead of one record at a
// time.
//
// Format (recordio.h:17-70): [magic u32][lrec u32][payload][pad to 4B] where
// lrec = cflag<<29 | length, cflag 0=whole 1=start 2=middle 3=end; payloads
// containing the aligned magic word are split at those words, which are
// re-inserted on read.

#include <cstdint>
#include <cstring>

#include "dmlc_tpu.h"

namespace {

constexpr uint32_t kMagic = 0xced7230aU;
constexpr uint32_t kLenMask = (1U << 29) - 1U;

inline uint32_t lower_align4(uint32_t x) { return x & ~3U; }
inline int64_t pad4(int64_t n) { return (n + 3) & ~int64_t(3); }

inline void put_u32(char* p, uint32_t v) { std::memcpy(p, &v, 4); }
inline uint32_t get_u32(const char* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

// Number of aligned magic words inside a payload (the reference's
// except_counter_, recordio.cc:16-23).
inline int64_t count_embedded_magic(const char* data, int64_t len) {
  int64_t n = 0;
  for (int64_t i = 0; i + 4 <= len; i += 4) {
    if (get_u32(data + i) == kMagic) ++n;
  }
  return n;
}

}  // namespace

extern "C" {

// Exact packed size of one record (header + payload + padding + extra
// headers for embedded-magic splits).
int64_t recordio_pack_bound(const char* data, int64_t len) {
  return 8 + pad4(len) + 8 * count_embedded_magic(data, len);
}

// Frame one record into out (caller sized via recordio_pack_bound).
// Returns bytes written. Mirrors WriteRecord (recordio.cc:11-51): payload is
// split at aligned embedded magic words; parts carry cflag start/middle/end.
int64_t recordio_pack(const char* data, int64_t len, char* out) {
  if (len >= (int64_t(1) << 29)) return -1;  // length field is 29 bits
  int64_t nmagic = count_embedded_magic(data, len);
  char* o = out;
  if (nmagic == 0) {
    put_u32(o, kMagic);
    put_u32(o + 4, static_cast<uint32_t>(len));
    std::memcpy(o + 8, data, len);
    o += 8 + len;
    while ((o - out) & 3) *o++ = 0;
    return o - out;
  }
  // split at each aligned embedded magic; the magic word itself is elided
  // (re-inserted by the reader between parts)
  int64_t part_start = 0;
  int64_t part_index = 0;
  for (int64_t i = 0; i + 4 <= len; i += 4) {
    if (get_u32(data + i) != kMagic) continue;
    int64_t plen = i - part_start;
    uint32_t cflag = (part_index == 0) ? 1U : 2U;
    put_u32(o, kMagic);
    put_u32(o + 4, (cflag << 29) | static_cast<uint32_t>(plen));
    std::memcpy(o + 8, data + part_start, plen);
    o += 8 + plen;
    while ((o - out) & 3) *o++ = 0;
    part_start = i + 4;
    ++part_index;
  }
  int64_t plen = len - part_start;
  put_u32(o, kMagic);
  put_u32(o + 4, (3U << 29) | static_cast<uint32_t>(plen));
  std::memcpy(o + 8, data + part_start, plen);
  o += 8 + plen;
  while ((o - out) & 3) *o++ = 0;
  return o - out;
}

// Exact packed size of a batch (one call instead of n ctypes round-trips).
int64_t recordio_pack_batch_bound(const char* data, const int64_t* offsets,
                                  int64_t n) {
  int64_t total = 0;
  for (int64_t r = 0; r < n; ++r) {
    total += recordio_pack_bound(data + offsets[r],
                                 offsets[r + 1] - offsets[r]);
  }
  return total;
}

// Batch pack: n records, payloads concatenated in data with offsets[n+1].
// out must hold the sum of per-record bounds. Returns bytes written.
int64_t recordio_pack_batch(const char* data, const int64_t* offsets,
                            int64_t n, char* out) {
  char* o = out;
  for (int64_t r = 0; r < n; ++r) {
    int64_t wrote =
        recordio_pack(data + offsets[r], offsets[r + 1] - offsets[r], o);
    if (wrote < 0) return -1;  // oversized record
    o += wrote;
  }
  return o - out;
}

// Unpack every complete record in buf[0:len] (must start at a record head).
// Reassembled payloads are written contiguously to out_data (re-inserting
// the magic between split parts, NextRecord recordio.cc:53-82), with
// out_offsets[r]..out_offsets[r+1] delimiting record r.
//   returns 0 ok; -2 corrupt framing
// *out_nrec = records decoded, *out_datalen = bytes written to out_data,
// *out_consumed = input bytes consumed (trailing partial frame is left).
int recordio_unpack(const char* buf, int64_t len, char* out_data,
                    int64_t* out_offsets, int64_t* out_nrec,
                    int64_t* out_datalen, int64_t* out_consumed) {
  int64_t pos = 0;
  int64_t nrec = 0;
  int64_t dlen = 0;
  out_offsets[0] = 0;
  int64_t rec_start = 0;        // current record's start in out_data
  int64_t rec_frame_start = 0;  // its first frame's offset in buf
  bool in_multi = false;
  while (pos + 8 <= len) {
    if (get_u32(buf + pos) != kMagic) return -2;
    uint32_t lrec = get_u32(buf + pos + 4);
    uint32_t cflag = lrec >> 29;
    int64_t plen = lrec & kLenMask;
    int64_t frame_end = pos + 8 + pad4(plen);
    if (frame_end > len) break;  // partial frame: stop
    if (!in_multi) {
      if (cflag != 0 && cflag != 1) return -2;
      rec_start = dlen;
      rec_frame_start = pos;
      in_multi = (cflag == 1);
    } else {
      if (cflag != 2 && cflag != 3) return -2;
      // re-insert the elided magic between parts
      put_u32(out_data + dlen, kMagic);
      dlen += 4;
    }
    std::memcpy(out_data + dlen, buf + pos + 8, plen);
    dlen += plen;
    pos = frame_end;
    if (cflag == 0 || cflag == 3) {
      out_offsets[++nrec] = dlen;
      in_multi = false;
    }
  }
  if (in_multi) {
    // incomplete multi-part record: roll both the payload AND the consumed
    // count back to the record's first frame, so callers see the truncation
    dlen = rec_start;
    pos = rec_frame_start;
  }
  *out_nrec = nrec;
  *out_datalen = dlen;
  *out_consumed = pos;
  return 0;
}

// First aligned offset >= start where a plausible record head begins
// (SeekRecordBegin, recordio_split.cc:9-25). Returns -1 if none.
int64_t recordio_find_head(const char* buf, int64_t len, int64_t start) {
  for (int64_t i = (start + 3) & ~int64_t(3); i + 8 <= len; i += 4) {
    if (get_u32(buf + i) == kMagic) {
      uint32_t cflag = get_u32(buf + i + 4) >> 29;
      if (cflag == 0 || cflag == 1) return i;
    }
  }
  return -1;
}

}  // extern "C"
