// Native ingest pipeline: reader thread -> parse workers -> ordered queue.
//
// TPU-build equivalent of the reference's threaded ingest composition:
// ThreadedInputSplit's chunk prefetch thread (src/io/threaded_input_split.h),
// ThreadedParser's parse producer (src/data/parser.h:70-126) and the OpenMP
// chunk parse team (src/data/text_parser.h:94-134) — rebuilt as one native
// pipeline so the Python layer only sees finished CSR blocks. Design differs
// from the reference: chunk-level (not intra-chunk) parallelism across a
// worker pool, sequence-numbered ordered delivery, and recycled chunk
// buffers (the ThreadedIter free-cell idea, threadediter.h:442-454) so
// steady state does no allocation on the reader side.
//
// Partitioning semantics are the reference's exactly-once contract
// (src/io/input_split_base.cc:30-64): part k of n covers global bytes
// [adj(k*step), adj((k+1)*step)) over the concatenated file sequence, where
// adj(x) scans forward from x to just past the next end-of-line run
// (line_split.cc:9-26) and adj(0) = 0. Every record lands in exactly one
// part for any n.

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <new>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <utility>
#include <thread>
#include <vector>

// POSIX (any unix): the mmap zero-copy reader
#include <fcntl.h>
#include <sys/mman.h>
#include <unistd.h>

#if defined(__GLIBC__)
#include <malloc.h>  // mallopt (TuneMallocOnce) is glibc-only
#endif

// The public header carries every cross-TU declaration (parse.cc hot
// loops, recordio.cc framing) — the compiler checks our definitions
// against it.
#include "dmlc_tpu.h"

namespace {

// Parsed-block output arrays are malloc'd per chunk and freed by whoever
// consumes the block (often Python, via the zero-copy numpy owner) — a
// free list can't span that boundary, but glibc tuning gets the same
// effect: keep big allocations on the heap (raise M_MMAP_THRESHOLD past
// the ~30 MB per-array bound) and never trim the heap top, so freed pages
// stay faulted-in and the next chunk's arrays land on warm memory.
// Measured on the criteo-shaped bench: ~600 -> ~670 MB/s chunked parse
// (page-fault + munmap churn was ~10-15% of the hot loop; matches a
// perfect reuse harness). Costs steady-state RSS at the pipeline's
// high-water mark. DMLC_TPU_MALLOC_TUNE=0 opts out.
void TuneMallocOnce() {
#if defined(__GLIBC__)
  static bool done = [] {
    const char* env = std::getenv("DMLC_TPU_MALLOC_TUNE");
    if (env != nullptr && env[0] == '0') return true;
    mallopt(M_MMAP_THRESHOLD, 64 * 1024 * 1024);
    mallopt(M_TRIM_THRESHOLD, 512 * 1024 * 1024);
    return true;
  }();
  (void)done;
#endif
}

enum Format { kLibsvm = 0, kLibfm = 1, kCsv = 2, kRecordIO = 3 };

// RecordIO framing constants (cpp/recordio.cc; reference recordio.h:17-70)
constexpr uint32_t kRioMagic = 0xced7230aU;

// Row-group payload: the binary row format carried inside RecordIO frames —
// the TPU build's answer to "binary shards must beat text parse" (the
// reference splits recordio natively, src/io/recordio_split.cc:9-82, but
// its data parsers are text-only; here the payload IS the CSR block, so
// ingest is framing + memcpy, no byte scanning). Layout, little-endian:
//   u8 tag 'R', u8 flags (1=weights 2=qids 4=values), u16 reserved,
//   u32 nrows, u32 nnz,
//   labels f32[nrows], weights f32[nrows]?, qids i64[nrows]?,
//   row_nnz u32[nrows], indices u32[nnz], values f32[nnz]?
constexpr uint8_t kRowGroupTag = 0x52;

enum {
  kOk = 0,
  kEOverflow = -1,
  kEParse = -2,
  kEIo = -3,
  kEOom = -4,
};

// row-flag bits mirrored from parse.cc (DMLC_TPU_HAS_*)
enum { kHasWeight = 1, kHasQid = 2, kHasValue = 4 };

inline int64_t NowNs() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

inline bool is_eol(char c) { return c == '\n' || c == '\r'; }

// Growable byte buffer without value-initialization: std::string/vector
// resize() zero-fills bytes that fread is about to overwrite — a full extra
// memory pass at ingest rates. Reserve leaves new capacity uninitialized.
struct Buf {
  char* p = nullptr;
  int64_t cap = 0;
  int64_t size = 0;

  ~Buf() { std::free(p); }
  Buf() = default;
  Buf(const Buf&) = delete;
  Buf& operator=(const Buf&) = delete;

  // false on allocation failure
  bool Reserve(int64_t n) {
    if (n <= cap) return true;
    int64_t want = std::max<int64_t>(n, cap * 2);
    char* np = static_cast<char*>(std::realloc(p, static_cast<size_t>(want)));
    if (np == nullptr) return false;
    p = np;
    cap = want;
    return true;
  }

  void Swap(Buf& other) {
    std::swap(p, other.p);
    std::swap(cap, other.cap);
    std::swap(size, other.size);
  }
};

struct Chunk {
  Buf data;
  int64_t seq = 0;
  // Borrowed view into the reader's mmap (zero-copy path): when set, the
  // chunk's bytes are ext[0..ext_len) and `data` stays empty. The mapping
  // outlives every in-flight chunk (munmap happens in Close after joins).
  const char* ext = nullptr;
  int64_t ext_len = 0;

  const char* ptr() const { return ext != nullptr ? ext : data.p; }
  int64_t len() const { return ext != nullptr ? ext_len : data.size; }
};

struct BlockPool;

// One parsed CSR batch. Buffers are malloc'd to a generous bound derived
// from the chunk length (every row and every token is >= 2 bytes, so
// len/2+2 bounds both) — untouched slack pages are virtual-only, which
// beats pre-scanning the chunk to size exactly. Indices/fields are u32
// storage written directly by the 32-bit parse variants.
//
// Returnable-block contract (extends the ThreadedIter recycle idea,
// threadediter.h:442-454, ACROSS the ownership boundary): a block whose
// text-parse arrays were sized to `cap_bound` elements can be returned to
// its origin pipeline's BlockPool instead of freed — the next chunk then
// parses into the SAME already-faulted pages. Release goes through
// ReleaseBlock() everywhere (including ingest_block_free, i.e. Python
// owners via the numpy-view finalizer), so the reuse survives the C ABI;
// blocks from the exact-size parsers (csv, recordio row-groups) keep
// cap_bound = 0 and always free. `pool` is reset while pooled so the
// free list never holds the refcount that keeps its own pool alive.
struct Block {
  float* labels = nullptr;
  float* weights = nullptr;
  float* values = nullptr;
  int64_t* qids = nullptr;
  int64_t* offsets = nullptr;
  uint32_t* indices = nullptr;
  uint32_t* fields = nullptr;
  int64_t rows = 0, nnz = 0, ncols = 0;
  int flags = 0;
  int64_t seq = 0;
  int64_t cap_bound = 0;  // text-parse array capacity (elements); 0 = not
                          // poolable (exact-size csv/recordio arrays)
  std::shared_ptr<BlockPool> pool;  // origin pipeline's pool, while alive

  void FreeArrays() {
    std::free(labels);
    std::free(weights);
    std::free(values);
    std::free(qids);
    std::free(offsets);
    std::free(indices);
    std::free(fields);
    labels = weights = values = nullptr;
    qids = offsets = nullptr;
    indices = fields = nullptr;
    cap_bound = 0;
  }

  ~Block() { FreeArrays(); }
};

// Bounded free list of recycled Blocks, shared between the pipeline's
// workers and whoever frees blocks (native consumers or Python GC, any
// thread). Outlives its Pipeline via shared_ptr from in-flight blocks:
// after Close(), returns route to plain delete.
struct BlockPool {
  std::mutex mu;
  std::vector<Block*> free_list;
  size_t cap = 8;
  bool closed = false;

  Block* Acquire() {
    std::lock_guard<std::mutex> lk(mu);
    if (free_list.empty()) return nullptr;
    Block* b = free_list.back();
    free_list.pop_back();
    return b;
  }

  // true when pooled; false -> caller deletes
  bool Put(Block* b) {
    std::lock_guard<std::mutex> lk(mu);
    if (closed || free_list.size() >= cap) return false;
    free_list.push_back(b);
    return true;
  }

  void Close() {
    std::vector<Block*> drop;
    {
      std::lock_guard<std::mutex> lk(mu);
      closed = true;
      drop.swap(free_list);
    }
    for (Block* b : drop) delete b;
  }
};

// The one release path for every Block regardless of owner: recycle into
// the origin pool when the block is poolable and the pipeline is still
// alive, else free. Per-parse fields are reset here (arrays and
// cap_bound survive — they are the point).
void ReleaseBlock(Block* b) {
  if (b == nullptr) return;
  std::shared_ptr<BlockPool> pool;
  pool.swap(b->pool);
  if (pool != nullptr && b->cap_bound > 0) {
    b->rows = b->nnz = b->ncols = 0;
    b->flags = 0;
    b->seq = 0;
    if (pool->Put(b)) return;
  }
  delete b;
}

template <typename T>
T* AllocArray(int64_t n) {
  return static_cast<T*>(std::malloc(static_cast<size_t>(n) * sizeof(T) + 1));
}

// Sequential reader over the concatenated file list, restricted to a global
// byte range (the reference's InputSplitBase::Read loop spanning file
// boundaries, input_split_base.cc:177-209).
class RangeReader {
 public:
  RangeReader(const std::vector<std::string>& paths,
              const std::vector<int64_t>& sizes)
      : paths_(paths), sizes_(sizes) {
    offsets_.push_back(0);
    for (int64_t s : sizes_) offsets_.push_back(offsets_.back() + s);
  }

  ~RangeReader() { CloseFile(); }

  int64_t total() const { return offsets_.back(); }

  bool SeekGlobal(int64_t pos) {
    CloseFile();
    pos_ = pos;
    if (pos >= total()) return true;
    file_idx_ = FileIndexFor(pos);
    if (!OpenFile(file_idx_)) return false;
    int64_t local = pos - offsets_[file_idx_];
    if (local != 0 && std::fseek(file_, static_cast<long>(local), SEEK_SET)) {
      return false;
    }
    return true;
  }

  // Read up to n bytes at the current position; 0 at end of file list,
  // -1 on I/O error.
  int64_t Read(char* buf, int64_t n) {
    int64_t got = 0;
    while (got < n) {
      if (file_ == nullptr) {
        if (pos_ >= total()) break;
        file_idx_ = FileIndexFor(pos_);
        if (!OpenFile(file_idx_)) return -1;
      }
      // never read past this file's declared size: a file that grew after
      // listing must not shift the global offset<->file mapping
      int64_t want = std::min<int64_t>(n - got, offsets_[file_idx_ + 1] - pos_);
      if (want <= 0) {
        CloseFile();
        if (file_idx_ + 1 >= static_cast<int64_t>(paths_.size())) break;
        continue;
      }
      size_t r = std::fread(buf + got, 1, static_cast<size_t>(want), file_);
      if (r > 0) {
        got += static_cast<int64_t>(r);
        pos_ += static_cast<int64_t>(r);
        continue;
      }
      if (std::ferror(file_)) return -1;
      // end of this file: advance to the next one
      CloseFile();
      if (pos_ != offsets_[file_idx_ + 1]) return -1;  // size changed underfoot
      if (file_idx_ + 1 >= static_cast<int64_t>(paths_.size())) break;
    }
    return got;
  }

  int64_t pos() const { return pos_; }

 private:
  int64_t FileIndexFor(int64_t pos) const {
    int64_t lo = 0, hi = static_cast<int64_t>(sizes_.size()) - 1;
    while (lo < hi) {
      int64_t mid = (lo + hi + 1) / 2;
      if (offsets_[mid] <= pos) lo = mid;
      else hi = mid - 1;
    }
    return lo;
  }

  bool OpenFile(int64_t idx) {
    CloseFile();
    file_ = std::fopen(paths_[idx].c_str(), "rb");
    return file_ != nullptr;
  }

  void CloseFile() {
    if (file_ != nullptr) {
      std::fclose(file_);
      file_ = nullptr;
    }
  }

  const std::vector<std::string> paths_;
  const std::vector<int64_t> sizes_;
  std::vector<int64_t> offsets_;
  FILE* file_ = nullptr;
  int64_t file_idx_ = 0;
  int64_t pos_ = 0;
};

class Pipeline {
 public:
  Pipeline(std::vector<std::string> paths, std::vector<int64_t> sizes,
           int format, int part, int nparts, int nthread, int64_t chunk_bytes,
           int capacity, int64_t csv_expect_cols, bool push_mode = false,
           int64_t shuffle_seed = -1)
      : paths_(std::move(paths)),
        sizes_(std::move(sizes)),
        format_(format),
        part_(part),
        nparts_(nparts),
        nthread_(nthread < 1 ? 1 : nthread),
        chunk_bytes_(chunk_bytes < (1 << 16) ? (1 << 16) : chunk_bytes),
        out_capacity_(capacity < 2 ? 2 : capacity),
        csv_expect_cols_(csv_expect_cols),
        push_mode_(push_mode),
        shuffle_seed_(shuffle_seed) {
    TuneMallocOnce();
    // DMLC_TPU_BLOCK_POOL=0 opts out (cap 0: every Put declines and
    // blocks free as before) — the A/B lever for measuring the recycle
    const char* env = std::getenv("DMLC_TPU_BLOCK_POOL");
    pool_->cap = (env != nullptr && env[0] == '0')
                     ? 0
                     : static_cast<size_t>(out_capacity_ + nthread_ + 4);
  }

  ~Pipeline() { Close(); }

  void Start() {
    if (!push_mode_) {
      reader_ = std::thread([this] {
        try {
          ReaderMain();
        } catch (const std::bad_alloc&) {
          Fail(kEOom);
        }
      });
    }
    for (int i = 0; i < nthread_; ++i) {
      workers_.emplace_back([this] { WorkerMain(); });
    }
  }

  // ---- push mode: the caller is the reader ----------------------------
  // Bytes arrive from Python-fetched remote chunks (parallel range-GET
  // readahead over gs://, s3://, hdfs://) instead of local fopen. The
  // caller must deliver the partition's byte range [begin, end) in order;
  // record-boundary cutting, parse fan-out and ordered delivery are the
  // same machinery the file reader uses. Blocks for backpressure when the
  // work queue is full (the ctypes call releases the GIL, so the Python
  // fetchers keep running). Returns 0, or the pipeline's error code.
  int Push(const char* data, int64_t len) {
    if (!push_mode_) return kEIo;
    int64_t off = 0;
    while (off < len) {
      {
        std::lock_guard<std::mutex> lk(mu_);
        if (stop_) return kEIo;
        if (error_ != 0) return error_;
      }
      int64_t want = std::min<int64_t>(len - off, chunk_bytes_);
      if (!push_tail_.Reserve(push_tail_.size + want)) {
        Fail(kEOom);
        return kEOom;
      }
      std::memcpy(push_tail_.p + push_tail_.size, data + off,
                  static_cast<size_t>(want));
      push_tail_.size += want;
      off += want;
      if (push_tail_.size < chunk_bytes_) continue;
      int64_t cut = LastRecordBegin(push_tail_);
      if (cut == 0) continue;  // no boundary yet: keep accumulating
      if (!EmitPushChunk(cut)) return kEIo;
    }
    return 0;
  }

  // Zero-copy variant of Push: the caller writes into the pipeline's own
  // tail buffer (HTTP readinto lands remote bytes directly in native
  // memory) and commits. The returned pointer is valid only until the
  // next Reserve/Commit/Push call. NULL on OOM or a failed pipeline.
  char* PushReserve(int64_t want) {
    if (!push_mode_ || want < 0) return nullptr;
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (stop_ || error_ != 0) return nullptr;
    }
    if (!push_tail_.Reserve(push_tail_.size + want)) {
      Fail(kEOom);
      return nullptr;
    }
    return push_tail_.p + push_tail_.size;
  }

  // Append n caller-written bytes to the tail and emit any complete
  // chunks (same cut discipline as Push; blocks for backpressure).
  int PushCommit(int64_t n) {
    if (!push_mode_ || n < 0) return kEIo;
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (stop_) return kEIo;
      if (error_ != 0) return error_;
    }
    push_tail_.size += n;
    while (push_tail_.size >= chunk_bytes_) {
      int64_t cut = LastRecordBegin(push_tail_);
      if (cut == 0) break;  // no boundary yet: keep accumulating
      if (!EmitPushChunk(cut)) return kEIo;
    }
    return 0;
  }

  // The pipeline's current error code (0 = healthy) — lets the push
  // driver report the REAL failure (e.g. a worker's kEParse) instead of
  // guessing from a null reserve.
  int LastError() {
    std::lock_guard<std::mutex> lk(mu_);
    return error_;
  }

  bool IsPushMode() const { return push_mode_; }

  // Flush the remaining tail (the caller guarantees the pushed range ends
  // at a record boundary, so the tail is whole records) and close the
  // stream. Idempotent. Returns 0, or the pipeline's error code.
  int PushEof() {
    if (!push_mode_) return kEIo;
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (reader_done_) return error_;
      if (error_ != 0) return error_;
    }
    if (push_tail_.size > 0 && !EmitPushChunk(push_tail_.size)) return kEIo;
    FinishReader(push_seq_);
    return 0;
  }

  // The Python feeder hit an unrecoverable fetch error: fail the pipeline
  // so blocked consumers wake with an error instead of hanging.
  void PushAbort() { Fail(kEIo); }

  // Wait for the next in-order block without consuming it.
  // 1 = block staged (sizes via *out), 0 = end of stream, <0 = error.
  int Peek(Block** out) {
    std::unique_lock<std::mutex> lk(mu_);
    for (;;) {
      if (error_ != 0) return error_;
      if (current_ != nullptr) {
        *out = current_;
        return 1;
      }
      auto it = done_.find(next_seq_out_);
      if (it != done_.end()) {
        current_ = it->second;
        done_.erase(it);
        ++next_seq_out_;
        cv_out_space_.notify_all();
        *out = current_;
        return 1;
      }
      if (reader_done_ && next_seq_out_ >= total_chunks_) return 0;
      int64_t t0 = NowNs();
      cv_out_.wait(lk);
      consumer_wait_ns_.fetch_add(NowNs() - t0);
    }
  }

  // Consume the staged block, copying into caller-owned buffers (any may be
  // null to skip). Returns 1, or 0 when nothing is staged.
  int Fetch(float* labels, float* weights, int64_t* qids, int64_t* offsets,
            uint32_t* indices, float* values, uint32_t* fields) {
    Block* b;
    {
      std::lock_guard<std::mutex> lk(mu_);
      b = current_;
      if (b == nullptr) return 0;
      current_ = nullptr;
    }
    size_t n = static_cast<size_t>(b->rows);
    size_t z = static_cast<size_t>(b->nnz);
    if (labels != nullptr) std::memcpy(labels, b->labels, n * 4);
    if (weights != nullptr) std::memcpy(weights, b->weights, n * 4);
    if (qids != nullptr) std::memcpy(qids, b->qids, n * 8);
    if (offsets != nullptr) std::memcpy(offsets, b->offsets, (n + 1) * 8);
    if (indices != nullptr) std::memcpy(indices, b->indices, z * 4);
    if (values != nullptr) std::memcpy(values, b->values, z * 4);
    if (fields != nullptr) std::memcpy(fields, b->fields, z * 4);
    ReleaseBlock(b);
    return 1;
  }

  // Consume the staged block, transferring ownership to the caller
  // (zero-copy handoff; the caller frees it via ingest_block_free).
  Block* FetchOwn() {
    std::lock_guard<std::mutex> lk(mu_);
    Block* b = current_;
    current_ = nullptr;
    return b;
  }

  // ---- consumer-side batch staging ------------------------------------
  // Fixed-shape re-batching in native code: the TPU feed consumes
  // [batch_size]-row batches with static shapes (device/csr.py's contract),
  // and doing the re-slice + densify in Python costs more than the parse
  // itself (BASELINE.md: 850 MB/s parse vs 244 MB/s feed). Staging pulls
  // parsed blocks in order and batch-fetch fills caller-owned buffers
  // (dense [batch, F] scatter or padded COO) directly from the CSR arrays —
  // the zero-copy handoff discipline of the reference's RowBlock
  // (src/data/row_block.h:169-188) extended through densify.
  //
  // Single-consumer API like Peek/Fetch: stage, then fetch consumes.

  // Stage >= batch_size rows (or all remaining). Returns 1 with
  // *rows/*nnz describing the next batch (rows = min(batch_size, staged)),
  // 0 at end of stream (no rows left), <0 on pipeline error.
  int StageBatch(int64_t batch_size, int64_t* out_rows, int64_t* out_nnz) {
    if (format_ == kCsv) return kEIo;  // csv blocks carry no CSR arrays
    while (staged_rows_ < batch_size) {
      Block* b = nullptr;
      int rc = Peek(&b);
      if (rc < 0) return rc;
      if (rc == 0) break;  // end of stream
      {
        std::lock_guard<std::mutex> lk(mu_);
        current_ = nullptr;  // take ownership
      }
      if (b->rows == 0) {
        ReleaseBlock(b);
        continue;
      }
      staged_.push_back(Span{b, 0});
      staged_rows_ += b->rows;
    }
    int64_t rows = std::min<int64_t>(batch_size, staged_rows_);
    *out_rows = rows;
    *out_nnz = NnzOfFirst(rows);
    return rows > 0 ? 1 : 0;
  }

  // Fill a dense [batch_size, num_features] f32 batch (plus labels/weights)
  // from the staged rows, consuming min(batch_size, staged) rows. Rows past
  // the valid count are zero (weight 0 ⇒ no-op in weighted losses). Feature
  // ids >= num_features are dropped, matching device/csr.py block_to_dense.
  // Returns rows consumed, or <0 (kEIo when the format has no CSR arrays).
  int64_t FetchBatchDense(float* x, float* labels, float* weights,
                          int64_t batch_size, int64_t num_features) {
    if (format_ == kCsv) return kEIo;
    // x is zeroed per-row (the dense-regular fast path writes only the
    // row's uncovered edges — a full upfront memset was ~40% of the
    // densify's memory traffic); padding rows are zeroed after the loop
    std::memset(labels, 0, static_cast<size_t>(batch_size) * 4);
    std::memset(weights, 0, static_cast<size_t>(batch_size) * 4);
    int64_t out_row = 0;
    while (out_row < batch_size && !staged_.empty()) {
      Span& sp = staged_.front();
      Block* b = sp.block;
      bool has_w = (b->flags & kHasWeight) != 0;
      bool has_v = format_ == kLibfm || (b->flags & kHasValue) != 0;
      const uint32_t* idx = b->indices;
      int64_t take = std::min<int64_t>(batch_size - out_row, b->rows - sp.row);
      for (int64_t i = 0; i < take; ++i) {
        int64_t r = sp.row + i;
        labels[out_row] = b->labels[r];
        weights[out_row] = has_w ? b->weights[r] : 1.0f;
        float* xrow = x + out_row * num_features;
        int64_t lo = b->offsets[r], hi = b->offsets[r + 1];
        // dense-regular fast path: a row whose indices are the
        // consecutive run [base, base+n) (the HIGGS/dense-table shape,
        // and every row-group written from dense data) densifies as ONE
        // memcpy instead of 28+ dependent scattered stores — the
        // densify was the dominant ingest->SGD stage (~60% of
        // host_batch time on the recordio bench). Cost: dense rows pay
        // one sequential O(n) compare scan (cheap next to the scatter it
        // replaces); sparse/irregular rows reject on the single
        // last-element compare below.
        int64_t n = hi - lo;
        if (has_v && n > 0 && static_cast<int64_t>(idx[lo]) + n <=
                                  num_features) {
          uint32_t base = idx[lo];
          // direct run check, cheapest-reject first (last element, then
          // the full scan with early exit) — no cached state, so sparse
          // rows with varying bases pay at most one compare
          bool regular = idx[hi - 1] == base + static_cast<uint32_t>(n - 1);
          for (int64_t k = 1; regular && k < n - 1; ++k) {
            regular = idx[lo + k] == base + static_cast<uint32_t>(k);
          }
          if (regular) {
            if (base > 0) std::memset(xrow, 0, static_cast<size_t>(base) * 4);
            std::memcpy(xrow + base, b->values + lo,
                        static_cast<size_t>(n) * 4);
            int64_t rest = num_features - base - n;
            if (rest > 0) {
              std::memset(xrow + base + n, 0,
                          static_cast<size_t>(rest) * 4);
            }
            ++out_row;
            continue;
          }
        }
        std::memset(xrow, 0, static_cast<size_t>(num_features) * 4);
        for (int64_t k = lo; k < hi; ++k) {
          uint32_t j = idx[k];
          if (j < static_cast<uint64_t>(num_features)) {
            xrow[j] = has_v ? b->values[k] : 1.0f;
          }
        }
        ++out_row;
      }
      ConsumeSpan(take);
    }
    if (out_row < batch_size) {  // zero-pad the short final batch
      std::memset(x + out_row * num_features, 0,
                  static_cast<size_t>((batch_size - out_row) *
                                      num_features) * 4);
    }
    return out_row;
  }

  // Fill a padded COO batch (labels/weights [batch_size]; indices/values/
  // row_ids [nnz_bucket]; offsets [batch_size + 1] CSR) from the staged
  // rows, consuming them. Padded entries are (row 0, feature 0, value 0) —
  // arithmetic no-ops for segment-sum SpMV; padded rows' offsets repeat the
  // valid nnz. The feed ships the small offsets array instead of the
  // per-entry row_ids (H2D ∝ rows, not nnz) and expands row ids on device;
  // row_ids stays filled for host-side consumers. Fails with kEOverflow
  // (consuming nothing) when the batch's nnz exceeds nnz_bucket. Returns
  // rows consumed, or <0.
  int64_t FetchBatchCoo(float* labels, float* weights, int32_t* indices,
                        float* values, int32_t* row_ids, int32_t* offsets,
                        int64_t batch_size, int64_t nnz_bucket) {
    if (format_ == kCsv) return kEIo;
    int64_t rows = std::min<int64_t>(batch_size, staged_rows_);
    if (NnzOfFirst(rows) > nnz_bucket) return kEOverflow;
    std::memset(labels, 0, static_cast<size_t>(batch_size) * 4);
    std::memset(weights, 0, static_cast<size_t>(batch_size) * 4);
    int64_t out_row = 0, out_k = 0;
    offsets[0] = 0;
    while (out_row < batch_size && !staged_.empty()) {
      Span& sp = staged_.front();
      Block* b = sp.block;
      bool has_w = (b->flags & kHasWeight) != 0;
      bool has_v = format_ == kLibfm || (b->flags & kHasValue) != 0;
      const uint32_t* idx = b->indices;
      int64_t take = std::min<int64_t>(batch_size - out_row, b->rows - sp.row);
      for (int64_t i = 0; i < take; ++i) {
        int64_t r = sp.row + i;
        labels[out_row] = b->labels[r];
        weights[out_row] = has_w ? b->weights[r] : 1.0f;
        for (int64_t k = b->offsets[r]; k < b->offsets[r + 1]; ++k) {
          indices[out_k] = static_cast<int32_t>(idx[k]);
          values[out_k] = has_v ? b->values[k] : 1.0f;
          row_ids[out_k] = static_cast<int32_t>(out_row);
          ++out_k;
        }
        ++out_row;
        offsets[out_row] = static_cast<int32_t>(out_k);
      }
      ConsumeSpan(take);
    }
    for (int64_t r = out_row + 1; r <= batch_size; ++r) {
      offsets[r] = static_cast<int32_t>(out_k);
    }
    for (int64_t k = out_k; k < nnz_bucket; ++k) {
      indices[k] = 0;
      values[k] = 0.0f;
      row_ids[k] = 0;
    }
    return out_row;
  }

  // Max per-shard nnz of the staged batch when its rows are split into
  // num_shards contiguous row ranges (the mesh dp sharding): the caller
  // sizes the shared per-shard bucket from this.
  int64_t StagedMaxShardNnz(int64_t batch_size, int64_t num_shards) const {
    if (num_shards <= 0 || batch_size % num_shards != 0) return -1;
    int64_t rows_per_shard = batch_size / num_shards;
    int64_t max_nnz = 0, cur = 0;
    int64_t row = 0, left = std::min<int64_t>(batch_size, staged_rows_);
    for (const Span& sp : staged_) {
      if (left <= 0) break;
      int64_t take = std::min<int64_t>(left, sp.block->rows - sp.row);
      for (int64_t i = 0; i < take; ++i) {
        int64_t r = sp.row + i;
        cur += sp.block->offsets[r + 1] - sp.block->offsets[r];
        if ((row + 1) % rows_per_shard == 0) {
          max_nnz = std::max(max_nnz, cur);
          cur = 0;
        }
        ++row;
      }
      left -= take;
    }
    return std::max(max_nnz, cur);
  }

  // Sharded COO fill: entries are partitioned by destination shard (row
  // range r/rows_per_shard) into per-shard sections of the flat
  // [num_shards * nnz_bucket] arrays, with LOCAL row ids — each device
  // receives only its own entries when the leading dim is sharded
  // (in_specs P(axis)), so per-device H2D is ∝ global_nnz / world instead
  // of replicating every entry to every shard. Padding entries are
  // (local row 0, feature 0, value 0) no-ops. Fails with kEOverflow
  // (consuming nothing) when any shard's nnz exceeds nnz_bucket.
  int64_t FetchBatchCooSharded(float* labels, float* weights,
                               int32_t* indices, float* values,
                               int32_t* row_ids, int32_t* offsets,
                               int64_t batch_size, int64_t num_shards,
                               int64_t nnz_bucket) {
    if (format_ == kCsv) return kEIo;
    if (num_shards <= 0 || batch_size % num_shards != 0) return kEIo;
    if (StagedMaxShardNnz(batch_size, num_shards) > nnz_bucket) {
      return kEOverflow;
    }
    int64_t rows_per_shard = batch_size / num_shards;
    // offsets: flat [num_shards * (rows_per_shard + 1)] — per-shard LOCAL
    // CSR offsets into that shard's entry section; the feed ships these
    // instead of per-entry row_ids and expands on device.
    std::memset(offsets, 0,
                static_cast<size_t>(num_shards * (rows_per_shard + 1)) * 4);
    std::vector<int64_t> filled(static_cast<size_t>(num_shards), 0);
    int64_t out_row = 0;
    int64_t cur = 0;  // entry cursor within the current shard's section
    while (out_row < batch_size && !staged_.empty()) {
      Span& sp = staged_.front();
      Block* b = sp.block;
      bool has_w = (b->flags & kHasWeight) != 0;
      bool has_v = format_ == kLibfm || (b->flags & kHasValue) != 0;
      const uint32_t* idx = b->indices;
      int64_t take = std::min<int64_t>(batch_size - out_row, b->rows - sp.row);
      for (int64_t i = 0; i < take; ++i) {
        int64_t r = sp.row + i;
        labels[out_row] = b->labels[r];
        weights[out_row] = has_w ? b->weights[r] : 1.0f;
        int64_t shard = out_row / rows_per_shard;
        int64_t local_row = out_row - shard * rows_per_shard;
        int64_t base = shard * nnz_bucket;
        for (int64_t k = b->offsets[r]; k < b->offsets[r + 1]; ++k) {
          indices[base + cur] = static_cast<int32_t>(idx[k]);
          values[base + cur] = has_v ? b->values[k] : 1.0f;
          row_ids[base + cur] = static_cast<int32_t>(local_row);
          ++cur;
        }
        ++out_row;
        offsets[shard * (rows_per_shard + 1) + local_row + 1] =
            static_cast<int32_t>(cur);
        if (out_row % rows_per_shard == 0) {
          filled[static_cast<size_t>(shard)] = cur;
          cur = 0;  // next shard section
        }
      }
      ConsumeSpan(take);
    }
    if (out_row > 0 && out_row % rows_per_shard != 0) {
      filled[static_cast<size_t>(out_row / rows_per_shard)] = cur;
    }
    // forward-fill each shard's offset tail (rows past the stream's end
    // repeat the shard's final nnz; untouched shards stay all-zero)
    for (int64_t s = 0; s < num_shards; ++s) {
      int32_t* off = offsets + s * (rows_per_shard + 1);
      int32_t run = 0;
      for (int64_t r = 1; r <= rows_per_shard; ++r) {
        run = std::max(run, off[r]);
        off[r] = run;
      }
    }
    // zero only the padding: row tail + each shard section's unfilled tail
    // (a full up-front memset would write most of the hot-path bytes twice)
    std::memset(labels + out_row, 0,
                static_cast<size_t>(batch_size - out_row) * 4);
    std::memset(weights + out_row, 0,
                static_cast<size_t>(batch_size - out_row) * 4);
    for (int64_t s = 0; s < num_shards; ++s) {
      int64_t base = s * nnz_bucket + filled[static_cast<size_t>(s)];
      size_t pad = static_cast<size_t>(
          nnz_bucket - filled[static_cast<size_t>(s)]);
      std::memset(indices + base, 0, pad * 4);
      std::memset(values + base, 0, pad * 4);
      std::memset(row_ids + base, 0, pad * 4);
    }
    return out_row;
  }

  // Per-stage counters for bench/diagnosis (SURVEY §5.1): where does wall
  // time go between reading, parsing and the consumer?
  void Stats(double* out, int32_t n) const {
    double vals[7] = {
        static_cast<double>(bytes_read_.load()),
        static_cast<double>(chunk_count_.load()),
        static_cast<double>(reader_io_ns_.load()),
        static_cast<double>(reader_wait_ns_.load()),
        static_cast<double>(parse_ns_.load()),
        static_cast<double>(worker_wait_ns_.load()),
        static_cast<double>(consumer_wait_ns_.load()),
    };
    for (int32_t i = 0; i < n && i < 7; ++i) out[i] = vals[i];
  }

  int64_t BytesRead() const { return bytes_read_.load(); }

  void Close() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (stop_) return;
      stop_ = true;
    }
    cv_work_.notify_all();
    cv_work_space_.notify_all();
    cv_out_.notify_all();
    cv_out_space_.notify_all();
    if (reader_.joinable()) reader_.join();
    for (auto& w : workers_)
      if (w.joinable()) w.join();
    for (auto& kv : done_) delete kv.second;
    done_.clear();
    for (Chunk* c : work_) delete c;
    work_.clear();
    for (Chunk* c : free_chunks_) delete c;
    free_chunks_.clear();
    if (current_ != nullptr) {
      delete current_;
      current_ = nullptr;
    }
    for (Span& sp : staged_) delete sp.block;
    staged_.clear();
    staged_rows_ = 0;
    // after this, blocks still owned by consumers (Python views) free
    // directly on release instead of returning here
    pool_->Close();
    // all chunk views are dead (reader + workers joined, queues cleared)
    if (map_base_ != nullptr) {
      ::munmap(map_base_, map_len_);
      map_base_ = nullptr;
      map_len_ = 0;
    }
  }

 private:
  // ---- batch staging state (single consumer thread only) --------------
  struct Span {
    Block* block;
    int64_t row;  // first unconsumed row
  };

  // nnz covered by the first `rows` staged rows
  int64_t NnzOfFirst(int64_t rows) const {
    int64_t nnz = 0;
    for (const Span& sp : staged_) {
      if (rows <= 0) break;
      int64_t take = std::min<int64_t>(rows, sp.block->rows - sp.row);
      nnz += sp.block->offsets[sp.row + take] - sp.block->offsets[sp.row];
      rows -= take;
    }
    return nnz;
  }

  // advance the front span by `rows`, retiring it when exhausted
  void ConsumeSpan(int64_t rows) {
    Span& sp = staged_.front();
    sp.row += rows;
    staged_rows_ -= rows;
    if (sp.row >= sp.block->rows) {
      ReleaseBlock(sp.block);
      staged_.pop_front();
    }
  }

  // Move the first `cut` bytes of push_tail_ into a work chunk; the
  // remainder becomes the new tail. False when the pipeline stopped.
  bool EmitPushChunk(int64_t cut) {
    Chunk* chunk = AcquireChunk();
    if (chunk == nullptr) return false;
    chunk->data.Swap(push_tail_);
    int64_t rest = chunk->data.size - cut;
    push_tail_.size = 0;
    if (rest > 0) {
      if (!push_tail_.Reserve(rest)) {
        delete chunk;
        Fail(kEOom);
        return false;
      }
      std::memcpy(push_tail_.p, chunk->data.p + cut,
                  static_cast<size_t>(rest));
      push_tail_.size = rest;
    }
    chunk->data.size = cut;
    if (cut == 0) {
      ReleaseChunk(chunk);
      return true;
    }
    chunk->seq = push_seq_++;
    return PushWork(chunk);
  }

  // ---- reader side ----------------------------------------------------
  // adj(x): first record-begin at global offset >= x (0 stays 0). Text
  // formats scan to the first EOL char then consume the whole EOL run, the
  // LineSplitter SeekRecordBegin contract (line_split.cc:9-26); recordio
  // scans aligned words for a head frame (recordio_split.cc:9-25 — exact,
  // not heuristic: packing elides aligned embedded magics, so an aligned
  // magic word can only be a frame head, and cflag 0/1 selects record
  // starts over continuations).
  int64_t AdjustBoundary(RangeReader* rd, int64_t x) {
    if (format_ == kRecordIO) return AdjustBoundaryRecordIO(rd, x);
    if (x <= 0) return 0;
    if (x >= rd->total()) return rd->total();
    if (!rd->SeekGlobal(x)) return -1;
    char buf[4096];
    bool seen_eol = false;
    int64_t pos = x;
    for (;;) {
      int64_t n = rd->Read(buf, sizeof(buf));
      if (n < 0) return -1;
      if (n == 0) return pos;
      for (int64_t i = 0; i < n; ++i) {
        if (is_eol(buf[i])) {
          seen_eol = true;
        } else if (seen_eol) {
          return pos + i;
        }
      }
      pos += n;
    }
  }

  int64_t AdjustBoundaryRecordIO(RangeReader* rd, int64_t x) {
    if (x <= 0) return 0;
    int64_t total = rd->total();
    if (x >= total) return total;
    int64_t base = (x + 3) & ~int64_t(3);  // heads sit on 4B alignment
    if (!rd->SeekGlobal(base)) return -1;
    char buf[4096 + 8];
    int64_t avail = 0;
    for (;;) {
      int64_t n = rd->Read(buf + avail, 4096);
      if (n < 0) return -1;
      avail += n;
      int64_t hit = recordio_find_head(buf, avail, 0);
      if (hit >= 0) return base + hit;
      if (n == 0) return total;  // no head before EOF
      // keep the unscanned aligned tail (< 8 bytes) for the next round
      int64_t processed = std::max<int64_t>(0, (avail - 4) & ~int64_t(3));
      std::memmove(buf, buf + processed, avail - processed);
      base += processed;
      avail -= processed;
    }
  }

  void ReaderMain() {
    RangeReader rd(paths_, sizes_);
    int64_t total = rd.total();
    // ceil-div step, matching input_split_base.cc:30-40; recordio rounds
    // the step to 4B alignment like the Python splitter (input_split.py
    // reset_partition) so both stacks assign boundary records to the SAME
    // part — a mixed native/fallback job must still tile exactly-once
    int64_t align = (format_ == kRecordIO) ? 4 : 1;
    int64_t nstep = (total + nparts_ - 1) / nparts_;
    nstep = (nstep + align - 1) / align * align;
    int64_t raw_begin = std::min<int64_t>(nstep * part_, total);
    int64_t raw_end = std::min<int64_t>(nstep * (part_ + 1), total);
    if (raw_begin >= raw_end) {
      FinishReader(0);
      return;
    }
    int64_t begin = AdjustBoundary(&rd, raw_begin);
    int64_t end = AdjustBoundary(&rd, raw_end);
    if (begin < 0 || end < 0) {
      Fail(kEIo);
      return;
    }
    if (begin >= end) {  // legitimately empty part (no record begins in
      FinishReader(0);   // its byte window) — zero rows, not an error
      return;
    }
    if (TryMmapReader(begin, end)) return;
    if (shuffle_seed_ >= 0) {
      // the caller asked for shuffled visit order and the zero-copy
      // reader declined (multi-file span, mmap failure): silent
      // sequential epochs would be a correctness lie for SGD
      Fail(kEIo);
      return;
    }
    if (!rd.SeekGlobal(begin)) {
      Fail(kEIo);
      return;
    }
    int64_t seq = 0;
    Buf tail;
    while (rd.pos() < end || tail.size > 0) {
      Chunk* chunk = AcquireChunk();
      if (chunk == nullptr) {  // stopped
        FinishReader(seq);
        return;
      }
      chunk->data.Swap(tail);
      tail.size = 0;
      int64_t target = chunk_bytes_;
      bool final_chunk = false;
      for (;;) {
        int64_t want = std::min<int64_t>(target - chunk->data.size,
                                         end - rd.pos());
        if (want > 0) {
          int64_t base = chunk->data.size;
          if (!chunk->data.Reserve(base + want)) {
            delete chunk;
            Fail(kEOom);
            return;
          }
          int64_t tr = NowNs();
          int64_t got = rd.Read(chunk->data.p + base, want);
          reader_io_ns_.fetch_add(NowNs() - tr);
          if (got < 0) {
            delete chunk;
            Fail(kEIo);
            return;
          }
          chunk->data.size = base + got;
          if (got < want) {
            // file list exhausted early (sizes changed): treat as final
            final_chunk = true;
            break;
          }
        }
        if (rd.pos() >= end) {
          final_chunk = true;
          break;
        }
        // cut at the last record begin inside the buffer
        int64_t cut = LastRecordBegin(chunk->data);
        if (cut > 0) {
          int64_t rest = chunk->data.size - cut;
          if (rest > 0) {
            if (!tail.Reserve(rest)) {
              delete chunk;
              Fail(kEOom);
              return;
            }
            std::memcpy(tail.p, chunk->data.p + cut,
                        static_cast<size_t>(rest));
          }
          tail.size = rest;
          chunk->data.size = cut;
          break;
        }
        // no boundary inside: grow and keep reading (Chunk::Load doubling,
        // input_split_base.cc:241-258)
        target *= 2;
      }
      if (chunk->data.size == 0) {
        ReleaseChunk(chunk);
        if (final_chunk) break;
        continue;
      }
      chunk->seq = seq++;
      if (!PushWork(chunk)) {
        FinishReader(seq);
        return;
      }
      if (final_chunk) break;
    }
    FinishReader(seq);
  }

  // Offset of the last record begin at index >= 1, or 0 when none. Text:
  // just past the last EOL char (line_split.cc FindLastRecordBegin).
  // RecordIO: the last aligned head frame (the chunk starts at a head, so
  // in-buffer heads stay 4B-aligned; see AdjustBoundary notes).
  int64_t LastRecordBegin(const char* p, int64_t size) const {
    if (format_ == kRecordIO) {
      for (int64_t i = (size - 8) & ~int64_t(3); i >= 4; i -= 4) {
        uint32_t w;
        std::memcpy(&w, p + i, 4);
        if (w != kRioMagic) continue;
        uint32_t lrec;
        std::memcpy(&lrec, p + i + 4, 4);
        uint32_t cflag = lrec >> 29;
        if (cflag == 0 || cflag == 1) return i;
      }
      return 0;
    }
    for (int64_t i = size - 1; i >= 1; --i) {
      if (is_eol(p[i])) return i + 1;
    }
    return 0;
  }

  int64_t LastRecordBegin(const Buf& buf) const {
    return LastRecordBegin(buf.p, buf.size);
  }

  // Zero-copy reader: serve the partition's chunks as borrowed views into
  // one mmap of the file instead of fread-ing into owned buffers. On a
  // host where reader and workers share cores (every TPU-host ingest is
  // CPU-bound on parse), the fread memcpy is pure serial overhead —
  // ~10-15% of wall on the criteo shape. Engages only when the whole
  // byte range lies inside ONE file (a record spanning two files needs
  // the copying reader's stitch loop); the mapping outlives in-flight
  // chunks (munmap in Close after joins). DMLC_TPU_MMAP=0 opts out
  // (e.g. files on file systems where SIGBUS-on-truncate is a concern —
  // the fread path misreads a concurrently truncated file, this one
  // faults; neither is a supported use).
  // Returns true when it served the range (or was stopped mid-way);
  // false -> caller runs the fread loop.
  bool TryMmapReader(int64_t begin, int64_t end) {
    const char* env = std::getenv("DMLC_TPU_MMAP");
    if (env != nullptr && env[0] == '0') return false;
    int file_idx = -1;
    int64_t file_base = 0, acc = 0;
    for (size_t i = 0; i < sizes_.size(); ++i) {
      if (begin >= acc && end <= acc + sizes_[i]) {
        file_idx = static_cast<int>(i);
        file_base = acc;
        break;
      }
      acc += sizes_[i];
    }
    if (file_idx < 0 || sizes_[file_idx] <= 0) return false;
    int64_t tr = NowNs();
    int fd = ::open(paths_[file_idx].c_str(), O_RDONLY);
    if (fd < 0) return false;
    size_t mlen = static_cast<size_t>(sizes_[file_idx]);
    void* base = ::mmap(nullptr, mlen, PROT_READ, MAP_PRIVATE, fd, 0);
    ::close(fd);
    if (base == MAP_FAILED) return false;
    ::madvise(base, mlen, MADV_SEQUENTIAL);
    map_base_ = base;
    map_len_ = mlen;
    reader_io_ns_.fetch_add(NowNs() - tr);
    const char* p = static_cast<const char*>(base);
    int64_t pos = begin - file_base;
    const int64_t le = end - file_base;
    int64_t seq = 0;
    if (shuffle_seed_ < 0) {
      // sequential: emit each chunk the moment its cut is known — the
      // boundary probe's page faults overlap parse work, and a stop
      // (AcquireChunk returning null) ends the scan promptly
      while (pos < le) {
        int64_t cut = NextCut(p, pos, le);
        if (cut > pos) {
          Chunk* chunk = AcquireChunk();
          if (chunk == nullptr) {  // stopped
            FinishReader(seq);
            return true;
          }
          chunk->ext = p + pos;
          chunk->ext_len = cut - pos;
          chunk->seq = seq++;
          if (!PushWork(chunk)) {
            FinishReader(seq);
            return true;
          }
        }
        pos = cut;
      }
      FinishReader(seq);
      return true;
    }
    // shuffle: phase 1 computes every chunk's [pos, cut) up front
    // (boundaries are data-deterministic, so a given (file, chunk_bytes)
    // always yields the same segment list), checking the stop flag so
    // ingest_close never blocks on a whole-part scan; phase 2 is a
    // seeded Fisher-Yates over mt19937_64 — the reference's
    // input_split_shuffle.h semantic (sub-splits visited in seeded
    // random order per epoch) at chunk granularity. std::shuffle is
    // implementation-defined; a shuffled EPOCH must be reproducible
    // from its seed alone. Random-access emission is only possible
    // here — the streaming reader cannot reorder without deadlocking
    // its bounded queues (ingest_open_ex refuses such requests).
    std::vector<std::pair<int64_t, int64_t>> segments;
    while (pos < le) {
      {
        std::lock_guard<std::mutex> lk(mu_);
        if (stop_ || error_ != 0) {
          FinishReader(0);
          return true;
        }
      }
      int64_t cut = NextCut(p, pos, le);
      if (cut > pos) segments.emplace_back(pos, cut);
      pos = cut;
    }
    if (segments.size() > 1) {
      std::mt19937_64 rng(static_cast<uint64_t>(shuffle_seed_));
      for (size_t i = segments.size() - 1; i > 0; --i) {
        size_t j = static_cast<size_t>(rng() % (i + 1));
        std::swap(segments[i], segments[j]);
      }
    }
    for (const auto& seg : segments) {
      Chunk* chunk = AcquireChunk();
      if (chunk == nullptr) {  // stopped
        FinishReader(seq);
        return true;
      }
      chunk->ext = p + seg.first;
      chunk->ext_len = seg.second - seg.first;
      chunk->seq = seq++;
      if (!PushWork(chunk)) {
        FinishReader(seq);
        return true;
      }
    }
    FinishReader(seq);
    return true;
  }

  // Next chunk cut in [pos, le): same discipline as the fread loop — last
  // record begin inside the window, doubling when a record outgrows it.
  int64_t NextCut(const char* p, int64_t pos, int64_t le) const {
    int64_t window = chunk_bytes_;
    for (;;) {
      int64_t target = std::min<int64_t>(pos + window, le);
      if (target >= le) return le;
      int64_t c = LastRecordBegin(p + pos, target - pos);
      if (c > 0) return pos + c;
      window *= 2;
    }
  }

  Chunk* AcquireChunk() {
    std::unique_lock<std::mutex> lk(mu_);
    // error_ must wake a backpressure-blocked producer (the push-mode
    // feeder especially: workers that exited on error stop draining work_,
    // and PushAbort/Fail would otherwise never unblock it)
    int64_t t0 = NowNs();
    cv_work_space_.wait(lk, [this] {
      return stop_ || error_ != 0 ||
             static_cast<int>(work_.size()) < nthread_ * 2;
    });
    reader_wait_ns_.fetch_add(NowNs() - t0);
    if (stop_ || error_ != 0) return nullptr;
    if (!free_chunks_.empty()) {
      Chunk* c = free_chunks_.back();
      free_chunks_.pop_back();
      c->data.size = 0;
      c->ext = nullptr;
      c->ext_len = 0;
      return c;
    }
    return new Chunk();
  }

  void ReleaseChunk(Chunk* c) {
    std::lock_guard<std::mutex> lk(mu_);
    free_chunks_.push_back(c);
  }

  bool PushWork(Chunk* chunk) {
    std::unique_lock<std::mutex> lk(mu_);
    if (stop_) {
      delete chunk;
      return false;
    }
    work_.push_back(chunk);
    cv_work_.notify_one();
    return true;
  }

  void FinishReader(int64_t nchunks) {
    std::lock_guard<std::mutex> lk(mu_);
    total_chunks_ = nchunks;
    reader_done_ = true;
    cv_work_.notify_all();
    cv_out_.notify_all();
  }

  void Fail(int code) {
    std::lock_guard<std::mutex> lk(mu_);
    if (error_ == 0) error_ = code;
    reader_done_ = true;
    cv_work_.notify_all();
    cv_out_.notify_all();
    cv_out_space_.notify_all();
    cv_work_space_.notify_all();
  }

  // ---- worker side ----------------------------------------------------
  void WorkerMain() {
    for (;;) {
      Chunk* chunk = nullptr;
      {
        std::unique_lock<std::mutex> lk(mu_);
        int64_t t0 = NowNs();
        cv_work_.wait(lk, [this] {
          return stop_ || error_ != 0 || !work_.empty() || reader_done_;
        });
        worker_wait_ns_.fetch_add(NowNs() - t0);
        if (stop_ || error_ != 0) return;
        if (work_.empty()) {
          if (reader_done_) return;
          continue;
        }
        chunk = work_.front();
        work_.pop_front();
        cv_work_space_.notify_one();
      }
      Block* block = nullptr;
      int rc;
      int64_t t0 = NowNs();
      try {
        block = pool_->Acquire();
        if (block == nullptr) block = new Block();
        block->pool = pool_;
        block->seq = chunk->seq;
        rc = ParseChunk(chunk->ptr(), chunk->len(), block);
      } catch (const std::bad_alloc&) {
        rc = kEOom;
      }
      parse_ns_.fetch_add(NowNs() - t0);
      chunk_count_.fetch_add(1);
      bytes_read_.fetch_add(chunk->len());
      ReleaseChunk(chunk);
      if (rc != kOk) {
        ReleaseBlock(block);
        Fail(rc);
        return;
      }
      std::unique_lock<std::mutex> lk(mu_);
      // the block the consumer is waiting for bypasses the capacity bound
      // so ordered delivery can never deadlock; an error or stop releases
      // every waiter
      cv_out_space_.wait(lk, [this, block] {
        return stop_ || error_ != 0 ||
               static_cast<int>(done_.size()) < out_capacity_ ||
               block->seq == next_seq_out_;
      });
      if (stop_ || error_ != 0) {
        ReleaseBlock(block);
        return;
      }
      done_.emplace(block->seq, block);
      cv_out_.notify_all();
    }
  }

  int ParseChunk(const char* p, int64_t len, Block* b) {
    if (format_ == kCsv) return ParseCsvChunk(p, len, b);
    if (format_ == kRecordIO) return ParseRecordIOChunk(p, len, b);
    int64_t bound = len / 2 + 2;  // rows and nnz are both >= 2 bytes each
    if (b->cap_bound < bound) {
      // recycled arrays too small (or a fresh block): (re)allocate the
      // full set at this bound. Equal-size chunks make this a one-time
      // cost per pooled block — steady state re-parses into warm pages.
      b->FreeArrays();
      b->labels = AllocArray<float>(bound);
      b->offsets = AllocArray<int64_t>(bound + 1);
      // u32 storage, filled directly by the 32-bit parse variants (no
      // narrowing pass); Block::indices stays a u64* holder by type only
      b->indices = AllocArray<uint32_t>(bound);
      b->values = AllocArray<float>(bound);
      if (b->labels == nullptr || b->offsets == nullptr ||
          b->indices == nullptr || b->values == nullptr) {
        return kEOom;
      }
      if (format_ == kLibsvm) {
        b->weights = AllocArray<float>(bound);
        b->qids = AllocArray<int64_t>(bound);
        if (b->weights == nullptr || b->qids == nullptr) return kEOom;
      } else {
        b->fields = AllocArray<uint32_t>(bound);
        if (b->fields == nullptr) return kEOom;
      }
      b->cap_bound = bound;
    }
    int64_t rows = 0, nnz = 0;
    int rc;
    if (format_ == kLibsvm) {
      rc = parse_libsvm32(p, len, b->labels, b->weights, b->qids,
                          b->offsets + 1,
                          b->indices, b->values,
                          bound, bound, &rows, &nnz, &b->flags);
    } else {
      rc = parse_libfm32(p, len, b->labels, b->offsets + 1,
                         b->fields, b->indices, b->values,
                         bound, bound, &rows, &nnz);
    }
    if (rc != kOk) return rc;
    b->rows = rows;
    b->nnz = nnz;
    // counts -> offsets prefix sum in place
    b->offsets[0] = 0;
    for (int64_t i = 1; i <= rows; ++i) b->offsets[i] += b->offsets[i - 1];
    return kOk;
  }

  int ParseCsvChunk(const char* p, int64_t len, Block* b) {
    int64_t max_rows = 2;
    for (const char* q = p; (q = static_cast<const char*>(std::memchr(
                                 q, '\n', static_cast<size_t>(p + len - q)))) !=
                            nullptr;
         ++q)
      ++max_rows;
    int64_t cols = csv_expect_cols_;
    if (cols <= 0) {
      // infer from the first line of this chunk
      cols = 1;
      for (int64_t i = 0; i < len && !is_eol(p[i]); ++i)
        if (p[i] == ',') ++cols;
    }
    b->values = AllocArray<float>(max_rows * cols);
    if (b->values == nullptr) return kEOom;
    int64_t rows = 0, out_cols = 0;
    int rc = parse_csv(p, len, b->values, max_rows, cols, &rows, &out_cols);
    if (rc != kOk) return rc;
    b->rows = rows;
    b->ncols = out_cols;
    b->nnz = rows * out_cols;
    return kOk;
  }

  // Decode a chunk of RecordIO-framed row groups into one CSR block: strip
  // the framing (recordio_unpack), then memcpy the typed sections — no text
  // scanning anywhere. Chunks are cut at record heads, so the frame stream
  // must decode completely.
  int ParseRecordIOChunk(const char* p, int64_t len, Block* b) {
    // reassembly re-inserts elided magics: output can exceed payload bytes
    // but never input length + one magic per frame
    Buf payload;
    if (!payload.Reserve(len + 4)) return kEOom;
    int64_t max_rec = len / 8 + 2;
    int64_t* offsets = AllocArray<int64_t>(max_rec + 1);
    if (offsets == nullptr) return kEOom;
    int64_t nrec = 0, dlen = 0, consumed = 0;
    int rc = recordio_unpack(p, len, payload.p, offsets, &nrec, &dlen,
                             &consumed);
    if (rc != 0 || consumed != len) {
      std::free(offsets);
      return kEParse;
    }
    // pass 1: header validation + totals
    int64_t rows = 0, nnz = 0;
    int flags = 0;
    for (int64_t r = 0; r < nrec; ++r) {
      const char* rp = payload.p + offsets[r];
      int64_t rlen = offsets[r + 1] - offsets[r];
      uint32_t n, z;
      uint8_t rflags;
      if (!RowGroupHeader(rp, rlen, &n, &z, &rflags)) {
        std::free(offsets);
        return kEParse;
      }
      rows += n;
      nnz += z;
      flags |= rflags;
    }
    b->labels = AllocArray<float>(rows + 1);
    b->offsets = AllocArray<int64_t>(rows + 1);
    b->indices = AllocArray<uint32_t>(nnz + 1);
    if (b->labels == nullptr || b->offsets == nullptr ||
        b->indices == nullptr) {
      std::free(offsets);
      return kEOom;
    }
    if (flags & kHasWeight) b->weights = AllocArray<float>(rows + 1);
    if (flags & kHasQid) b->qids = AllocArray<int64_t>(rows + 1);
    if (flags & kHasValue) b->values = AllocArray<float>(nnz + 1);
    if (((flags & kHasWeight) && b->weights == nullptr) ||
        ((flags & kHasQid) && b->qids == nullptr) ||
        ((flags & kHasValue) && b->values == nullptr)) {
      std::free(offsets);
      return kEOom;
    }
    // pass 2: memcpy the sections
    uint32_t* idx_out = b->indices;
    int64_t row_at = 0, nnz_at = 0;
    b->offsets[0] = 0;
    for (int64_t r = 0; r < nrec; ++r) {
      const char* rp = payload.p + offsets[r];
      uint32_t n = 0, z = 0;
      uint8_t rflags = 0;  // header re-read; validated in pass 1
      RowGroupHeader(rp, offsets[r + 1] - offsets[r], &n, &z, &rflags);
      const char* q = rp + 12;
      std::memcpy(b->labels + row_at, q, n * 4);
      q += int64_t(n) * 4;
      if (rflags & kHasWeight) {
        std::memcpy(b->weights + row_at, q, n * 4);
        q += int64_t(n) * 4;
      } else if (flags & kHasWeight) {
        for (uint32_t i = 0; i < n; ++i) b->weights[row_at + i] = 1.0f;
      }
      if (rflags & kHasQid) {
        std::memcpy(b->qids + row_at, q, n * 8);
        q += int64_t(n) * 8;
      } else if (flags & kHasQid) {
        std::memset(b->qids + row_at, 0, n * 8);
      }
      // row_nnz -> running offsets
      const uint32_t* row_nnz = reinterpret_cast<const uint32_t*>(q);
      for (uint32_t i = 0; i < n; ++i) {
        b->offsets[row_at + i + 1] =
            b->offsets[row_at + i] + row_nnz[i];
      }
      q += int64_t(n) * 4;
      std::memcpy(idx_out + nnz_at, q, z * 4);
      q += int64_t(z) * 4;
      if (rflags & kHasValue) {
        std::memcpy(b->values + nnz_at, q, z * 4);
      } else if (flags & kHasValue) {
        for (uint32_t k = 0; k < z; ++k) b->values[nnz_at + k] = 1.0f;
      }
      row_at += n;
      nnz_at += z;
    }
    std::free(offsets);
    if (b->offsets[rows] != nnz) return kEParse;  // row_nnz vs nnz mismatch
    b->rows = rows;
    b->nnz = nnz;
    b->flags = flags;
    return kOk;
  }

  // Validate one row-group payload; false on malformed. Exact-size check
  // keeps a corrupt length from driving the memcpys past the payload.
  static bool RowGroupHeader(const char* p, int64_t len, uint32_t* nrows,
                             uint32_t* nnz, uint8_t* flags) {
    if (len < 12) return false;
    if (static_cast<uint8_t>(p[0]) != kRowGroupTag) return false;
    uint8_t fl = static_cast<uint8_t>(p[1]);
    if (fl & ~uint8_t(kHasWeight | kHasQid | kHasValue)) return false;
    uint32_t n, z;
    std::memcpy(&n, p + 4, 4);
    std::memcpy(&z, p + 8, 4);
    int64_t want = 12 + int64_t(n) * 4 + int64_t(n) * 4 + int64_t(z) * 4;
    if (fl & kHasWeight) want += int64_t(n) * 4;
    if (fl & kHasQid) want += int64_t(n) * 8;
    if (fl & kHasValue) want += int64_t(z) * 4;
    if (want != len) return false;
    *nrows = n;
    *nnz = z;
    *flags = fl;
    return true;
  }

  // ---- state ----------------------------------------------------------
  const std::vector<std::string> paths_;
  const std::vector<int64_t> sizes_;
  const int format_;
  const int part_, nparts_;
  const int nthread_;
  const int64_t chunk_bytes_;
  const int out_capacity_;
  const int64_t csv_expect_cols_;
  const bool push_mode_;

  // push-mode state: only touched by the single pushing thread
  Buf push_tail_;
  int64_t push_seq_ = 0;

  // batch-staging state: only touched by the single consuming thread
  std::deque<Span> staged_;
  int64_t staged_rows_ = 0;

  // per-stage counters (ns); written by their owning threads, read by Stats
  std::atomic<int64_t> reader_io_ns_{0};
  std::atomic<int64_t> reader_wait_ns_{0};
  std::atomic<int64_t> parse_ns_{0};
  std::atomic<int64_t> worker_wait_ns_{0};
  std::atomic<int64_t> consumer_wait_ns_{0};
  std::atomic<int64_t> chunk_count_{0};

  std::thread reader_;
  std::vector<std::thread> workers_;

  std::mutex mu_;
  std::condition_variable cv_work_, cv_work_space_, cv_out_, cv_out_space_;
  std::deque<Chunk*> work_;
  std::vector<Chunk*> free_chunks_;
  // returnable parsed blocks (see Block/BlockPool): sized past the
  // in-flight bound (out queue + one per worker + staging slack) so a
  // prompt consumer's returns always find room
  std::shared_ptr<BlockPool> pool_ = std::make_shared<BlockPool>();
  // seeded chunk-shuffle (ingest_open_ex); -1 = sequential
  const int64_t shuffle_seed_ = -1;
  // zero-copy reader mapping (TryMmapReader); unmapped in Close
  void* map_base_ = nullptr;
  size_t map_len_ = 0;
  std::map<int64_t, Block*> done_;
  int64_t next_seq_out_ = 0;
  int64_t total_chunks_ = -1;
  bool reader_done_ = false;
  bool stop_ = false;
  int error_ = 0;
  std::atomic<int64_t> bytes_read_{0};
  Block* current_ = nullptr;
};

}  // namespace

extern "C" {

// paths: '\0'-joined (nfiles entries); sizes: byte size per file.
// format: 0=libsvm 1=libfm 2=csv. Returns NULL on bad args.
void* ingest_open_ex(const char* paths, const int64_t* sizes, int32_t nfiles,
                     int32_t format, int32_t part, int32_t nparts,
                     int32_t nthread, int64_t chunk_bytes, int32_t capacity,
                     int64_t csv_expect_cols, int64_t shuffle_seed) {
  if (nfiles <= 0 || part < 0 || nparts <= 0 || part >= nparts) return nullptr;
  if (format < 0 || format > 3) return nullptr;
  if (shuffle_seed >= 0) {
    // shuffled visit order needs the random-access mmap reader: refuse
    // up front what the reader could only fail at runtime (multi-file
    // datasets span mappings; DMLC_TPU_MMAP=0 opts the reader out)
    const char* env = std::getenv("DMLC_TPU_MMAP");
    if (nfiles != 1 || (env != nullptr && env[0] == '0')) return nullptr;
  }
  std::vector<std::string> path_vec;
  const char* p = paths;
  for (int32_t i = 0; i < nfiles; ++i) {
    path_vec.emplace_back(p);
    p += path_vec.back().size() + 1;
  }
  std::vector<int64_t> size_vec(sizes, sizes + nfiles);
  Pipeline* pl =
      new Pipeline(std::move(path_vec), std::move(size_vec), format, part,
                   nparts, nthread, chunk_bytes, capacity, csv_expect_cols,
                   /*push_mode=*/false, shuffle_seed);
  pl->Start();
  return pl;
}

void* ingest_open(const char* paths, const int64_t* sizes, int32_t nfiles,
                  int32_t format, int32_t part, int32_t nparts,
                  int32_t nthread, int64_t chunk_bytes, int32_t capacity,
                  int64_t csv_expect_cols) {
  return ingest_open_ex(paths, sizes, nfiles, format, part, nparts, nthread,
                        chunk_bytes, capacity, csv_expect_cols,
                        /*shuffle_seed=*/-1);
}

// Push-mode pipeline: no reader thread — the caller streams the partition's
// bytes in with ingest_push (Python-fetched remote chunks feed the same
// native parse workers and ordered queue as local files). End the stream
// with ingest_push_eof; on a fetch failure call ingest_push_abort so
// consumers blocked in ingest_peek fail instead of hanging.
void* ingest_open_push(int32_t format, int32_t nthread, int64_t chunk_bytes,
                       int32_t capacity, int64_t csv_expect_cols) {
  if (format < 0 || format > 3) return nullptr;
  Pipeline* pl = new Pipeline({}, {}, format, 0, 1, nthread, chunk_bytes,
                              capacity, csv_expect_cols, /*push_mode=*/true);
  pl->Start();
  return pl;
}

// Append len bytes of the partition stream. Blocks for backpressure when
// the parse workers are behind. Returns 0 or a pipeline error code.
int ingest_push(void* handle, const char* data, int64_t len) {
  return static_cast<Pipeline*>(handle)->Push(data, len);
}

int ingest_push_eof(void* handle) {
  return static_cast<Pipeline*>(handle)->PushEof();
}

// Zero-copy push: reserve tail space to write into (valid until the next
// reserve/commit/push), then commit the bytes written. Feeders use this to
// readinto() remote responses directly into pipeline memory.
void* ingest_push_reserve(void* handle, int64_t want) {
  return static_cast<Pipeline*>(handle)->PushReserve(want);
}

int ingest_push_commit(void* handle, int64_t n) {
  return static_cast<Pipeline*>(handle)->PushCommit(n);
}

void ingest_push_abort(void* handle) {
  static_cast<Pipeline*>(handle)->PushAbort();
}

// Serial reserve -> caller-fetch -> commit loop over the whole stream (the
// C-consumer twin of the Python readahead feeder; see the header for the
// transport-boundary contract). Backpressure comes from PushCommit's
// bounded work queue, exactly as for any other feeder.
int ingest_drive_push(void* handle, dmlc_tpu_fetch_fn fetch, void* ctx,
                      int64_t total, int64_t fetch_bytes) {
  Pipeline* pl = static_cast<Pipeline*>(handle);
  // handle misuse (a reader-mode handle from ingest_open) is rejected
  // up front WITHOUT failing the pipeline — the sibling push_* calls
  // return kEIo the same way, and aborting a healthy reader pipeline
  // would wedge its consumers for the caller's mistake
  if (fetch == nullptr || !pl->IsPushMode()) return kEIo;
  if (fetch_bytes <= 0) fetch_bytes = 1 << 20;
  int64_t off = 0;
  while (total < 0 || off < total) {
    int64_t want = fetch_bytes;
    if (total >= 0 && total - off < want) want = total - off;
    if (want == 0) break;
    char* dst = pl->PushReserve(want);
    if (dst == nullptr) {
      // null here (push mode checked above) means the pipeline already
      // failed (worker parse error — report its real code), was stopped
      // by a concurrent close (kEIo), or hit OOM (PushReserve already
      // failed the pipeline with kEOom); no extra abort needed
      int err = pl->LastError();
      return err != 0 ? err : kEIo;
    }
    int64_t got = fetch(ctx, off, dst, want);
    if (got < 0 || got > want) {
      pl->PushAbort();
      return kEIo;
    }
    if (got == 0) {
      if (total >= 0) {
        // premature EOF against a declared length (object truncated
        // between stat and read, short HTTP body): consumers must see a
        // failure, not a clean EOF with rows missing
        pl->PushAbort();
        return kEIo;
      }
      break;  // end of stream (unknown-length mode)
    }
    int rc = pl->PushCommit(got);
    if (rc != 0) return rc;
    off += got;
  }
  return pl->PushEof();
}

// Wait for the next in-order block and report its sizes without consuming
// it. Returns 1 (sizes filled), 0 at end of stream, <0 on error. Idempotent
// until ingest_fetch consumes the staged block.
int ingest_peek(void* handle, int64_t* rows, int64_t* nnz, int64_t* ncols,
                int32_t* flags) {
  Pipeline* pl = static_cast<Pipeline*>(handle);
  Block* b = nullptr;
  int rc = pl->Peek(&b);
  if (rc != 1) return rc;
  *rows = b->rows;
  *nnz = b->nnz;
  *ncols = b->ncols;
  *flags = b->flags;
  return 1;
}

// Copy the staged block into caller-owned buffers (sized per ingest_peek;
// any pointer may be NULL to skip that array; indices/fields receive u32)
// and consume it. Returns 1, or 0 when no block is staged.
int ingest_fetch(void* handle, float* labels, float* weights, int64_t* qids,
                 int64_t* offsets, uint32_t* indices, float* values,
                 uint32_t* fields) {
  return static_cast<Pipeline*>(handle)->Fetch(labels, weights, qids, offsets,
                                               indices, values, fields);
}

// Zero-copy variant of ingest_fetch: transfers ownership of the staged
// block. Fills the output array pointers (indices/fields point at
// u32-packed data; pointers not populated by the format are NULL, but for
// libsvm the weights/qids arrays are always allocated with their defaults —
// presence of *explicit* weights/qids is signaled by the flags from
// ingest_peek, not by pointer nullness) and returns an opaque block handle
// the caller must release with ingest_block_free once the arrays are no
// longer referenced. Returns NULL when no block is staged.
void* ingest_fetch_view(void* handle, float** labels, float** weights,
                        int64_t** qids, int64_t** offsets, uint32_t** indices,
                        float** values, uint32_t** fields) {
  Block* b = static_cast<Pipeline*>(handle)->FetchOwn();
  if (b == nullptr) return nullptr;
  *labels = b->labels;
  *weights = b->weights;
  *qids = b->qids;
  *offsets = b->offsets;
  *indices = b->indices;
  *values = b->values;
  *fields = b->fields;
  return b;
}

void ingest_block_free(void* block) {
  // routes poolable blocks back to their origin pipeline's free list
  // (cross-ABI recycle); frees otherwise
  ReleaseBlock(static_cast<Block*>(block));
}

// ---- native batch staging (fixed-shape TPU feed) -------------------------
// Stage the next batch of up to batch_size rows (pulling parsed blocks in
// order; partial blocks carry over). Fills *rows (min(batch_size, left))
// and *nnz for sizing the fetch buffers. Returns 1 when rows > 0, 0 at end
// of stream, <0 on pipeline error. Single consumer thread, like
// ingest_peek/ingest_fetch.
int ingest_stage_batch(void* handle, int64_t batch_size, int64_t* rows,
                       int64_t* nnz) {
  return static_cast<Pipeline*>(handle)->StageBatch(batch_size, rows, nnz);
}

// Consume the staged rows into a dense [batch_size, num_features] f32 image
// plus labels/weights (zero-padded past the valid rows; weights default 1
// for valid rows). Returns rows consumed, or <0 on error.
int64_t ingest_fetch_batch_dense(void* handle, float* x, float* labels,
                                 float* weights, int64_t batch_size,
                                 int64_t num_features) {
  return static_cast<Pipeline*>(handle)->FetchBatchDense(
      x, labels, weights, batch_size, num_features);
}

// Consume the staged rows into a padded COO batch: labels/weights
// [batch_size], indices/values/row_ids [nnz_bucket], offsets
// [batch_size + 1] CSR (padding = arithmetic no-ops for segment-sum).
// Fails with -1 (consuming nothing) when the batch nnz exceeds
// nnz_bucket. Returns rows consumed, or <0 on error.
int64_t ingest_fetch_batch_coo(void* handle, float* labels, float* weights,
                               int32_t* indices, float* values,
                               int32_t* row_ids, int32_t* offsets,
                               int64_t batch_size, int64_t nnz_bucket) {
  return static_cast<Pipeline*>(handle)->FetchBatchCoo(
      labels, weights, indices, values, row_ids, offsets, batch_size,
      nnz_bucket);
}

// Max per-shard nnz of the staged batch under a num_shards row-range
// split (for sizing the shared per-shard bucket). -1 on bad arguments.
int64_t ingest_staged_max_shard_nnz(void* handle, int64_t batch_size,
                                    int64_t num_shards) {
  return static_cast<Pipeline*>(handle)->StagedMaxShardNnz(batch_size,
                                                           num_shards);
}

// Consume the staged rows into a mesh-sharded COO batch: labels/weights
// [batch_size]; indices/values/row_ids flat [num_shards * nnz_bucket] with
// per-shard sections and LOCAL row ids (shard = row / (batch/num_shards));
// offsets flat [num_shards * (batch/num_shards + 1)] per-shard LOCAL CSR.
// Fails with -1 (consuming nothing) when any shard overflows nnz_bucket.
int64_t ingest_fetch_batch_coo_sharded(void* handle, float* labels,
                                       float* weights, int32_t* indices,
                                       float* values, int32_t* row_ids,
                                       int32_t* offsets,
                                       int64_t batch_size,
                                       int64_t num_shards,
                                       int64_t nnz_bucket) {
  return static_cast<Pipeline*>(handle)->FetchBatchCooSharded(
      labels, weights, indices, values, row_ids, offsets, batch_size,
      num_shards, nnz_bucket);
}

// Per-stage counters: out[0]=bytes_read, [1]=chunks, [2]=reader_io_ns,
// [3]=reader_wait_ns, [4]=parse_ns, [5]=worker_wait_ns, [6]=consumer_wait_ns.
void ingest_stats(void* handle, double* out, int32_t n) {
  static_cast<Pipeline*>(handle)->Stats(out, n);
}

int64_t ingest_bytes_read(void* handle) {
  return static_cast<Pipeline*>(handle)->BytesRead();
}

void ingest_close(void* handle) {
  Pipeline* pl = static_cast<Pipeline*>(handle);
  pl->Close();
  delete pl;
}

}  // extern "C"
