// Native hot loops: text chunk -> CSR arrays.
//
// TPU-build equivalent of the reference's parse path (src/data/strtonum.h,
// libsvm_parser.h, libfm_parser.h, csv_parser.h): the chunk-level tokenize +
// numeric-convert loop is the ingest bottleneck, so it lives in C++ behind a
// flat C ABI (ctypes-loadable, zero Python objects inside). Design differs
// from the reference: single forward scan with branch-light inline float
// parsing, caller-allocated output arrays (upper bounds derived from the
// chunk), and row/nnz counts returned for exact trimming. No OpenMP — the
// Python side maps chunk pieces onto a thread pool and ctypes releases the
// GIL, so parallelism composes at the chunk level.

#include <cstdint>
#include <cstring>

#include "dmlc_tpu.h"

namespace {

inline bool is_space(char c) { return c == ' ' || c == '\t'; }

// '\r' is a line terminator (LineSplitter record boundaries accept \n, \r,
// and \r\n), never inline whitespace — treating it as a space would merge
// adjacent rows.
inline bool is_eol(char c) { return c == '\n' || c == '\r'; }

inline bool is_digit(char c) { return c >= '0' && c <= '9'; }

// Exact powers of ten: 10^k is representable exactly in a double for
// k <= 22, so mantissa*10^k / mantissa/10^k round once — the classic fast
// strtod fast path.
const double kPow10[23] = {
    1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,  1e8,  1e9,  1e10, 1e11,
    1e12, 1e13, 1e14, 1e15, 1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};

inline double ApplyExp10(double val, int64_t exp10) {
  if (exp10 == 0) return val;
  // |exp10| beyond ±350 already saturates to ±inf / ±0 for any mantissa the
  // scan can produce (<= 1e19); clamping bounds the loop for adversarial
  // exponents like 1e-999999999. The clamp happens HERE, after the explicit
  // exponent has been folded in, so compensating pairs (long zero run +
  // large positive exponent) stay exact.
  if (exp10 > 350) exp10 = 350;
  else if (exp10 < -350) exp10 = -350;
  if (exp10 > 0) {
    while (exp10 > 22) { val *= 1e22; exp10 -= 22; }
    return val * kPow10[exp10];
  }
  exp10 = -exp10;
  while (exp10 > 22) { val /= 1e22; exp10 -= 22; }
  return val / kPow10[exp10];
}

// SWAR helpers for the fraction hot path: classify 8 bytes at once and
// convert a full 8-digit group with a multiply tree instead of a serial
// per-digit loop. `y` is the chunk XOR 0x30..30, so digit bytes are 0..9.
// Returns the count of leading (lowest-address-first) digit bytes and masks
// *digits down to them. Carry-free: the add is done on 7-bit bytes.
inline int CountDigits8(uint64_t y, uint64_t* digits) {
  uint64_t y7 = y & 0x7F7F7F7F7F7F7F7FULL;
  uint64_t nondigit =
      (((y7 + 0x7676767676767676ULL) | y) & 0x8080808080808080ULL);
  if (nondigit == 0) {
    *digits = y;
    return 8;
  }
  int k = __builtin_ctzll(nondigit) >> 3;
  *digits = y & ((1ULL << (k * 8)) - 1);
  return k;
}

// 8 ascii-stripped digit bytes (lowest address = most significant digit,
// little-endian load) -> the 8-digit number. Three multiplies total.
inline uint32_t Swar8Digits(uint64_t y) {
  const uint64_t mask = 0x000000FF000000FFULL;
  const uint64_t mul1 = 0x000F424000000064ULL;  // 100 + (1000000 << 32)
  const uint64_t mul2 = 0x0000271000000001ULL;  // 1 + (10000 << 32)
  y = (y * 10) + (y >> 8);
  return static_cast<uint32_t>(
      (((y & mask) * mul1) + (((y >> 16) & mask) * mul2)) >> 32);
}

// Fast float scan: sign, integer part, fraction, optional exponent.
// Handles the common data-file cases inline; no INF/NAN/hex (same contract
// as the reference's strtonum.h:37, by design: data files don't contain
// them, and rejecting keeps the loop branch-light). Digits accumulate into
// an integer mantissa (pipelinable integer ops, no serial FP chain); the
// decimal exponent is applied once at the end via exact powers of ten.
inline const char* scan_double(const char* p, const char* end, double* out) {
  if (p == end) return nullptr;
  bool neg = false;
  if (*p == '-') { neg = true; ++p; }
  else if (*p == '+') { ++p; }
  if (p == end || (!is_digit(*p) && *p != '.')) return nullptr;
  uint64_t mant = 0;
  int ndig = 0;   // significant digits folded into mant (19 max: fits uint64)
  // int64: bounded by the input length, so digit/zero runs can't overflow
  // it; saturation is applied once in ApplyExp10 after the explicit
  // exponent is added (a mid-scan cap would corrupt compensating pairs
  // like "0.<420 zeros>5e450").
  int64_t exp10 = 0;
  // ndig += (mant != 0) keeps leading zeros mantissa-budget-free without a
  // branch in the hot loop (folding a 0 into mant==0 is a numeric no-op).
  while (p != end && is_digit(*p)) {
    if (ndig < 19) {
      mant = mant * 10 + static_cast<uint64_t>(*p - '0');
      ndig += static_cast<int>(mant != 0);
    } else {
      ++exp10;
    }
    ++p;
  }
  if (p != end && *p == '.') {
    ++p;
    // 8-wide groups while the mantissa has room (mant*1e8 + 8 digits must
    // fit uint64: safe while ndig <= 11). A short group (k < 8) appends
    // 8-k virtual zero digits — value-preserving for a fraction tail, and
    // the byte at p+k is a real non-digit so the scalar loop below exits
    // immediately. An all-zero group before any significant digit shifts
    // the decimal point but costs no mantissa budget, so long zero runs
    // ("0.<420 zeros>5") skip 8 bytes at a time with their significant
    // digits preserved.
    while (end - p >= 8 && ndig <= 11) {
      uint64_t chunk;
      std::memcpy(&chunk, p, 8);
      uint64_t digs;
      int k = CountDigits8(chunk ^ 0x3030303030303030ULL, &digs);
      if (k == 0) break;
      // branchless: folding an all-zero group into a zero mantissa is a
      // numeric no-op, and ndig charges 8 only once a significant digit
      // has appeared
      mant = mant * 100000000ULL + Swar8Digits(digs);
      ndig += static_cast<int>(mant != 0) << 3;
      exp10 -= 8;
      p += k;
      if (k < 8) break;
    }
    while (p != end && is_digit(*p)) {
      if (ndig < 19) {
        mant = mant * 10 + static_cast<uint64_t>(*p - '0');
        ndig += static_cast<int>(mant != 0);
        --exp10;
      }
      ++p;
    }
  }
  if (p != end && (*p == 'e' || *p == 'E')) {
    ++p;
    bool eneg = false;
    if (p != end && (*p == '-' || *p == '+')) { eneg = (*p == '-'); ++p; }
    int ex = 0;
    while (p != end && is_digit(*p)) {
      if (ex < 100000000) ex = ex * 10 + (*p - '0');
      ++p;
    }
    exp10 += eneg ? -ex : ex;
  }
  *out = ApplyExp10(neg ? -static_cast<double>(mant)
                        : static_cast<double>(mant),
                    exp10);
  return p;
}

inline const char* scan_u64(const char* p, const char* end, uint64_t* out) {
  if (p == end || !is_digit(*p)) return nullptr;
  uint64_t v = 0;
  while (p != end && is_digit(*p)) { v = v * 10 + (*p - '0'); ++p; }
  *out = v;
  return p;
}

const uint64_t kPow10U64[9] = {1ULL,       10ULL,       100ULL,
                               1000ULL,    10000ULL,    100000ULL,
                               1000000ULL, 10000000ULL, 100000000ULL};

// SWAR u64 scan for LONG digit runs (high-cardinality feature ids: Criteo's
// 7-digit hashed ids). Classify 8 bytes at once, then convert the k leading
// digits in one multiply tree: the k digit bytes (most significant at the
// lowest address) are shifted up so Swar8Digits sees them as the LEAST
// significant digit positions behind leading zeros — value-exact, no
// division. ~constant ~20 ops per <=8-digit run vs a 4-5 cycle/digit serial
// mul-add chain; loses on 1-2 digit ids (measured 45% slower if applied
// unconditionally — see BASELINE.md round-3 notes), so callers pick it
// per-chunk from observed id lengths.
inline const char* scan_u64_swar(const char* p, const char* end,
                                 uint64_t* out) {
  if (p == end || !is_digit(*p)) return nullptr;
  uint64_t v = 0;
  while (end - p >= 8) {
    uint64_t chunk;
    std::memcpy(&chunk, p, 8);
    uint64_t digs;
    int k = CountDigits8(chunk ^ 0x3030303030303030ULL, &digs);
    if (k == 0) break;
    v = v * kPow10U64[k] + Swar8Digits(digs << ((8 - k) * 8));
    p += k;
    if (k < 8) { *out = v; return p; }
  }
  while (p != end && is_digit(*p)) { v = v * 10 + (*p - '0'); ++p; }
  *out = v;
  return p;
}

}  // namespace

// Status codes and feature flags come from the public header
// (dmlc_tpu.h) — the single source the Python binding and external
// consumers read.


// Parse libfm text: "label field:idx:val ..." per line. Outputs as libsvm
// plus fields [max_nnz].
template <typename IndexT>
static int parse_libfm_impl(const char* data, int64_t len,
                float* labels, int64_t* row_nnz,
                IndexT* fields, IndexT* indices, float* values,
                int64_t max_rows, int64_t max_nnz,
                int64_t* out_rows, int64_t* out_nnz) {
  const char* p = data;
  const char* end = data + len;
  int64_t rows = 0, nnz = 0;
  // Adaptive id scan, as in parse_libsvm_impl: first row's average idx
  // length picks serial vs SWAR-group conversion for the chunk.
  bool long_ids = false;
  int64_t id_bytes = 0, id_count = 0;
  while (p != end) {
    while (p != end && (is_space(*p) || is_eol(*p))) ++p;
    if (p == end) break;
    double label;
    const char* q = scan_double(p, end, &label);
    if (q == nullptr) return DMLC_TPU_EPARSE;
    p = q;
    if (rows >= max_rows) return DMLC_TPU_EOVERFLOW;
    int64_t row_start = nnz;
    for (;;) {
      while (p != end && is_space(*p)) ++p;
      if (p == end || is_eol(*p)) {
        if (p != end) ++p;
        break;
      }
      uint64_t field, idx;
      double val;
      q = scan_u64(p, end, &field);
      if (q == nullptr || q == end || *q != ':') return DMLC_TPU_EPARSE;
      const char* idx_start = q + 1;
      q = long_ids ? scan_u64_swar(idx_start, end, &idx)
                   : scan_u64(idx_start, end, &idx);
      if (q == nullptr || q == end || *q != ':') return DMLC_TPU_EPARSE;
      if (rows == 0) { id_bytes += q - idx_start; ++id_count; }
      q = scan_double(q + 1, end, &val);
      if (q == nullptr) return DMLC_TPU_EPARSE;
      p = q;
      if (nnz >= max_nnz) return DMLC_TPU_EOVERFLOW;
      fields[nnz] = static_cast<IndexT>(field);
      indices[nnz] = static_cast<IndexT>(idx);
      values[nnz] = static_cast<float>(val);
      ++nnz;
    }
    labels[rows] = static_cast<float>(label);
    row_nnz[rows] = nnz - row_start;
    ++rows;
    if (rows == 1) long_ids = id_count > 0 && id_bytes >= 5 * id_count;  // avg >= 5 digits
  }
  *out_rows = rows;
  *out_nnz = nnz;
  return DMLC_TPU_OK;
}

// Templated over the index width: the pipeline consumes u32 indices, and
// writing them directly saves a whole narrowing pass over nnz (the
// NarrowU64ToU32 sweep used to re-read 8 and re-write 4 bytes per entry).
template <typename IndexT>
static int parse_libsvm_impl(const char* data, int64_t len,
                 float* labels, float* weights, int64_t* qids,
                 int64_t* row_nnz, IndexT* indices, float* values,
                 int64_t max_rows, int64_t max_nnz,
                 int64_t* out_rows, int64_t* out_nnz, int* out_flags) {
  const char* p = data;
  const char* end = data + len;
  int64_t rows = 0, nnz = 0;
  int flags = 0;
  // Adaptive id scan: the first row's average id length picks serial vs
  // SWAR-group conversion for the whole chunk (files are homogeneous;
  // HIGGS-class 1-2 digit ids lose on SWAR classify overhead, Criteo-class
  // 7-digit hashed ids win ~constant-time conversion).
  bool long_ids = false;
  int64_t id_bytes = 0, id_count = 0;
  while (p != end) {
    while (p != end && (is_space(*p) || is_eol(*p))) ++p;
    if (p == end) break;
    // label [:weight]
    double label;
    const char* q = scan_double(p, end, &label);
    if (q == nullptr) return DMLC_TPU_EPARSE;
    p = q;
    double weight = 1.0;
    if (p != end && *p == ':') {
      ++p;
      q = scan_double(p, end, &weight);
      if (q == nullptr) return DMLC_TPU_EPARSE;
      p = q;
      flags |= DMLC_TPU_HAS_WEIGHT;
    }
    if (rows >= max_rows) return DMLC_TPU_EOVERFLOW;
    // missing qid -> 0, matching RowBlockContainer's neutral-default policy
    // (and the pure-Python twin)
    int64_t qid = 0;
    int64_t row_start = nnz;
    // features until newline
    for (;;) {
      while (p != end && is_space(*p)) ++p;
      if (p == end || is_eol(*p)) {
        if (p != end) ++p;
        break;
      }
      if (*p == 'q' && end - p > 4 && std::memcmp(p, "qid:", 4) == 0) {
        uint64_t qv;
        q = scan_u64(p + 4, end, &qv);
        if (q == nullptr) return DMLC_TPU_EPARSE;
        qid = static_cast<int64_t>(qv);
        flags |= DMLC_TPU_HAS_QID;
        p = q;
        continue;
      }
      uint64_t idx;
      q = long_ids ? scan_u64_swar(p, end, &idx) : scan_u64(p, end, &idx);
      if (q == nullptr) return DMLC_TPU_EPARSE;
      if (rows == 0) { id_bytes += q - p; ++id_count; }
      p = q;
      double val = 1.0;
      if (p != end && *p == ':') {
        ++p;
        q = scan_double(p, end, &val);
        if (q == nullptr) return DMLC_TPU_EPARSE;
        p = q;
        flags |= DMLC_TPU_HAS_VALUE;
      }
      if (nnz >= max_nnz) return DMLC_TPU_EOVERFLOW;
      indices[nnz] = static_cast<IndexT>(idx);
      values[nnz] = static_cast<float>(val);
      ++nnz;
    }
    labels[rows] = static_cast<float>(label);
    weights[rows] = static_cast<float>(weight);
    qids[rows] = qid;
    row_nnz[rows] = nnz - row_start;
    ++rows;
    if (rows == 1) long_ids = id_count > 0 && id_bytes >= 5 * id_count;  // avg >= 5 digits
  }
  *out_rows = rows;
  *out_nnz = nnz;
  *out_flags = flags;
  return DMLC_TPU_OK;
}


extern "C" {

// Parse libsvm text: "label[:weight] [qid:n] idx[:val] ..." per line.
// Outputs: labels/weights [max_rows], qids [max_rows], row_nnz [max_rows],
// indices/values [max_nnz] — u64 indices (the original ctypes ABI). Rows
// with no explicit weight get 1.0; bare indices get value 1.0. Returns
// DMLC_TPU_OK/errors; *out_rows, *out_nnz, *out_flags filled on success.
int parse_libsvm(const char* data, int64_t len,
                 float* labels, float* weights, int64_t* qids,
                 int64_t* row_nnz, uint64_t* indices, float* values,
                 int64_t max_rows, int64_t max_nnz,
                 int64_t* out_rows, int64_t* out_nnz, int* out_flags) {
  return parse_libsvm_impl<uint64_t>(
      data, len, labels, weights, qids, row_nnz, indices, values, max_rows,
      max_nnz, out_rows, out_nnz, out_flags);
}

// u32-index variant for the native pipeline's device-layout buffers
// (values past 2^32 truncate exactly like the old narrowing pass did).
int parse_libsvm32(const char* data, int64_t len,
                   float* labels, float* weights, int64_t* qids,
                   int64_t* row_nnz, uint32_t* indices, float* values,
                   int64_t max_rows, int64_t max_nnz,
                   int64_t* out_rows, int64_t* out_nnz, int* out_flags) {
  return parse_libsvm_impl<uint32_t>(
      data, len, labels, weights, qids, row_nnz, indices, values, max_rows,
      max_nnz, out_rows, out_nnz, out_flags);
}

int parse_libfm(const char* data, int64_t len,
                float* labels, int64_t* row_nnz,
                uint64_t* fields, uint64_t* indices, float* values,
                int64_t max_rows, int64_t max_nnz,
                int64_t* out_rows, int64_t* out_nnz) {
  return parse_libfm_impl<uint64_t>(data, len, labels, row_nnz, fields,
                                    indices, values, max_rows, max_nnz,
                                    out_rows, out_nnz);
}

// u32 variant for the native pipeline (see parse_libsvm32).
int parse_libfm32(const char* data, int64_t len,
                  float* labels, int64_t* row_nnz,
                  uint32_t* fields, uint32_t* indices, float* values,
                  int64_t max_rows, int64_t max_nnz,
                  int64_t* out_rows, int64_t* out_nnz) {
  return parse_libfm_impl<uint32_t>(data, len, labels, row_nnz, fields,
                                    indices, values, max_rows, max_nnz,
                                    out_rows, out_nnz);
}

// Parse dense CSV (no quoting — numeric data files): every line becomes
// ncols doubles; the first line fixes ncols. Outputs values row-major into
// out [max_rows * expect_cols]. If expect_cols == 0 it is inferred and
// written to *out_cols.
int parse_csv(const char* data, int64_t len, float* out,
              int64_t max_rows, int64_t expect_cols,
              int64_t* out_rows, int64_t* out_cols) {
  const char* p = data;
  const char* end = data + len;
  int64_t rows = 0;
  int64_t ncols = expect_cols;
  while (p != end) {
    while (p != end && is_eol(*p)) ++p;
    if (p == end) break;
    if (rows >= max_rows) return DMLC_TPU_EOVERFLOW;
    int64_t col = 0;
    float* row_out = out + rows * (ncols > 0 ? ncols : 0);
    for (;;) {
      double val = 0.0;
      while (p != end && is_space(*p)) ++p;
      if (p != end && *p != ',' && !is_eol(*p)) {
        const char* q = scan_double(p, end, &val);
        if (q == nullptr) return DMLC_TPU_EPARSE;
        p = q;
        while (p != end && is_space(*p)) ++p;
      }
      if (ncols > 0) {
        if (col >= ncols) return DMLC_TPU_EPARSE;
        row_out[col] = static_cast<float>(val);
      } else {
        // inference pass for first row: caller guarantees capacity via
        // max_rows * (commas in first line + 1)
        out[col] = static_cast<float>(val);
      }
      ++col;
      if (p == end || is_eol(*p)) {
        if (p != end) ++p;
        break;
      }
      if (*p != ',') return DMLC_TPU_EPARSE;
      ++p;
    }
    if (ncols <= 0) {
      ncols = col;
      row_out = out;
    } else if (col != ncols) {
      return DMLC_TPU_EPARSE;
    }
    ++rows;
  }
  *out_rows = rows;
  *out_cols = ncols;
  return DMLC_TPU_OK;
}

// One-pass upper-bound counter for output sizing: *out_rows = newline count
// + 1, *out_tokens = whitespace-delimited token count (>= nnz + rows).
void count_tokens(const char* data, int64_t len,
                  int64_t* out_rows, int64_t* out_tokens) {
  int64_t rows = 1, tokens = 0;
  bool in_tok = false;
  for (int64_t i = 0; i < len; ++i) {
    char c = data[i];
    if (is_eol(c)) {
      ++rows;
      in_tok = false;
    } else if (is_space(c)) {
      in_tok = false;
    } else if (!in_tok) {
      in_tok = true;
      ++tokens;
    }
  }
  *out_rows = rows;
  *out_tokens = tokens;
}

int dmlc_tpu_abi_version(void) { return DMLC_TPU_ABI_VERSION; }

}  // extern "C"
