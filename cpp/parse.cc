// Native hot loops: text chunk -> CSR arrays.
//
// TPU-build equivalent of the reference's parse path (src/data/strtonum.h,
// libsvm_parser.h, libfm_parser.h, csv_parser.h): the chunk-level tokenize +
// numeric-convert loop is the ingest bottleneck, so it lives in C++ behind a
// flat C ABI (ctypes-loadable, zero Python objects inside). Design differs
// from the reference: single forward scan with branch-light inline float
// parsing, caller-allocated output arrays (upper bounds derived from the
// chunk), and row/nnz counts returned for exact trimming. No OpenMP — the
// Python side maps chunk pieces onto a thread pool and ctypes releases the
// GIL, so parallelism composes at the chunk level.
//
// The LibSVM path additionally runtime-dispatches to the AVX2 tokenize +
// batch-convert engine in parse_simd.cc (SimdKernelLevel() gates on CPUID
// and DMLC_TPU_SIMD); the scalar loop below is both the portable fallback
// and the row-level oracle the SIMD engine defers to for anything outside
// its fast shapes, so results are bit-identical either way.

#include <cstdint>
#include <cstring>

#include "dmlc_tpu.h"
#include "parse_common.h"

using namespace dmlc_tpu_parse;

// Status codes and feature flags come from the public header
// (dmlc_tpu.h) — the single source the Python binding and external
// consumers read.


// Parse libfm text: "label field:idx:val ..." per line. Outputs as libsvm
// plus fields [max_nnz].
template <typename IndexT>
static int parse_libfm_impl(const char* data, int64_t len,
                float* labels, int64_t* row_nnz,
                IndexT* fields, IndexT* indices, float* values,
                int64_t max_rows, int64_t max_nnz,
                int64_t* out_rows, int64_t* out_nnz) {
  const char* p = data;
  const char* end = data + len;
  int64_t rows = 0, nnz = 0;
  // Adaptive id scan, as in parse_libsvm_impl: first row's average idx
  // length picks serial vs SWAR-group conversion for the chunk.
  bool long_ids = false;
  int64_t id_bytes = 0, id_count = 0;
  while (p != end) {
    while (p != end && (is_space(*p) || is_eol(*p))) ++p;
    if (p == end) break;
    double label;
    const char* q = scan_double(p, end, &label);
    if (q == nullptr) return DMLC_TPU_EPARSE;
    p = q;
    if (rows >= max_rows) return DMLC_TPU_EOVERFLOW;
    int64_t row_start = nnz;
    for (;;) {
      while (p != end && is_space(*p)) ++p;
      if (p == end || is_eol(*p)) {
        if (p != end) ++p;
        break;
      }
      uint64_t field, idx;
      double val;
      q = scan_u64(p, end, &field);
      if (q == nullptr || q == end || *q != ':') return DMLC_TPU_EPARSE;
      const char* idx_start = q + 1;
      q = long_ids ? scan_u64_swar(idx_start, end, &idx)
                   : scan_u64(idx_start, end, &idx);
      if (q == nullptr || q == end || *q != ':') return DMLC_TPU_EPARSE;
      if (rows == 0) { id_bytes += q - idx_start; ++id_count; }
      q = scan_double(q + 1, end, &val);
      if (q == nullptr) return DMLC_TPU_EPARSE;
      p = q;
      if (nnz >= max_nnz) return DMLC_TPU_EOVERFLOW;
      fields[nnz] = static_cast<IndexT>(field);
      indices[nnz] = static_cast<IndexT>(idx);
      values[nnz] = static_cast<float>(val);
      ++nnz;
    }
    labels[rows] = static_cast<float>(label);
    row_nnz[rows] = nnz - row_start;
    ++rows;
    if (rows == 1) long_ids = id_count > 0 && id_bytes >= 5 * id_count;  // avg >= 5 digits
  }
  *out_rows = rows;
  *out_nnz = nnz;
  return DMLC_TPU_OK;
}

// First-line shape probe for kernel dispatch: average feature-id length in
// bytes. The AVX2 engine amortizes its tokenize+batch-convert tiles best on
// long tokens (Criteo-class 6-7 digit hashed ids: ~16% over scalar); on
// HIGGS-class 1-2 digit ids the scalar SWAR loop's per-byte costs are
// already near the floor and the engine's extra passes lose ~8%. Same
// homogeneity assumption as the long_ids SWAR pick below, sampled without
// parsing: bytes from each token start to its first ':' (or token end).
static bool ProbeLongIds(const char* data, int64_t len) {
  int64_t cap = len < 2048 ? len : 2048;
  int64_t i = 0;
  while (i < cap && (is_space(data[i]) || is_eol(data[i]))) ++i;
  int64_t id_bytes = 0, id_count = 0;
  bool first_tok = true;  // the label doesn't count
  while (i < cap && !is_eol(data[i])) {
    while (i < cap && is_space(data[i])) ++i;
    if (i >= cap || is_eol(data[i])) break;
    int64_t tok = i, colon = -1;
    while (i < cap && !is_space(data[i]) && !is_eol(data[i])) {
      if (colon < 0 && data[i] == ':') colon = i;
      ++i;
    }
    if (!first_tok) {
      id_bytes += (colon >= 0 ? colon : i) - tok;
      ++id_count;
    }
    first_tok = false;
  }
  return id_count > 0 && id_bytes >= 5 * id_count;  // avg >= 5 digits
}

// Templated over the index width: the pipeline consumes u32 indices, and
// writing them directly saves a whole narrowing pass over nnz (the
// NarrowU64ToU32 sweep used to re-read 8 and re-write 4 bytes per entry).
template <typename IndexT>
static int parse_libsvm_impl(const char* data, int64_t len,
                 float* labels, float* weights, int64_t* qids,
                 int64_t* row_nnz, IndexT* indices, float* values,
                 int64_t max_rows, int64_t max_nnz,
                 int64_t* out_rows, int64_t* out_nnz, int* out_flags) {
  SvmSink<IndexT> sink{labels,   weights, qids, row_nnz, indices, values,
                       max_rows, max_nnz, 0,    0,       0};
  // AVX2 engine when the CPU has it, the chunk is big enough to amortize
  // its tile setup (tiny chunks — unit-test strings — stay scalar), and the
  // first-line probe says the token shape favors it. DMLC_TPU_SIMD=1 forces
  // the engine regardless of shape (parity tests exercise it that way).
  if (len >= 256 && SimdKernelLevel() >= 2 &&
      (SimdKernelForced() || ProbeLongIds(data, len))) {
    int rc = ParseSvmSimd(data, len, &sink);
    if (rc != DMLC_TPU_OK) return rc;
    *out_rows = sink.rows;
    *out_nnz = sink.nnz;
    *out_flags = sink.flags;
    return DMLC_TPU_OK;
  }
  const char* p = data;
  const char* end = data + len;
  // Adaptive id scan: the first row's average id length picks serial vs
  // SWAR-group conversion for the whole chunk (files are homogeneous;
  // HIGGS-class 1-2 digit ids lose on SWAR classify overhead, Criteo-class
  // 7-digit hashed ids win ~constant-time conversion).
  bool long_ids = false;
  int64_t id_bytes = 0, id_count = 0;
  while (p != end) {
    while (p != end && (is_space(*p) || is_eol(*p))) ++p;
    if (p == end) break;
    bool first = sink.rows == 0;
    int rc = ParseSvmRowScalar<IndexT>(&p, end, long_ids,
                                       first ? &id_bytes : nullptr,
                                       first ? &id_count : nullptr, &sink);
    if (rc != DMLC_TPU_OK) return rc;
    if (sink.rows == 1)
      long_ids = id_count > 0 && id_bytes >= 5 * id_count;  // avg >= 5 digits
  }
  *out_rows = sink.rows;
  *out_nnz = sink.nnz;
  *out_flags = sink.flags;
  return DMLC_TPU_OK;
}


extern "C" {

// Parse libsvm text: "label[:weight] [qid:n] idx[:val] ..." per line.
// Outputs: labels/weights [max_rows], qids [max_rows], row_nnz [max_rows],
// indices/values [max_nnz] — u64 indices (the original ctypes ABI). Rows
// with no explicit weight get 1.0; bare indices get value 1.0. Returns
// DMLC_TPU_OK/errors; *out_rows, *out_nnz, *out_flags filled on success.
int parse_libsvm(const char* data, int64_t len,
                 float* labels, float* weights, int64_t* qids,
                 int64_t* row_nnz, uint64_t* indices, float* values,
                 int64_t max_rows, int64_t max_nnz,
                 int64_t* out_rows, int64_t* out_nnz, int* out_flags) {
  return parse_libsvm_impl<uint64_t>(
      data, len, labels, weights, qids, row_nnz, indices, values, max_rows,
      max_nnz, out_rows, out_nnz, out_flags);
}

// u32-index variant for the native pipeline's device-layout buffers
// (values past 2^32 truncate exactly like the old narrowing pass did).
int parse_libsvm32(const char* data, int64_t len,
                   float* labels, float* weights, int64_t* qids,
                   int64_t* row_nnz, uint32_t* indices, float* values,
                   int64_t max_rows, int64_t max_nnz,
                   int64_t* out_rows, int64_t* out_nnz, int* out_flags) {
  return parse_libsvm_impl<uint32_t>(
      data, len, labels, weights, qids, row_nnz, indices, values, max_rows,
      max_nnz, out_rows, out_nnz, out_flags);
}

int parse_libfm(const char* data, int64_t len,
                float* labels, int64_t* row_nnz,
                uint64_t* fields, uint64_t* indices, float* values,
                int64_t max_rows, int64_t max_nnz,
                int64_t* out_rows, int64_t* out_nnz) {
  return parse_libfm_impl<uint64_t>(data, len, labels, row_nnz, fields,
                                    indices, values, max_rows, max_nnz,
                                    out_rows, out_nnz);
}

// u32 variant for the native pipeline (see parse_libsvm32).
int parse_libfm32(const char* data, int64_t len,
                  float* labels, int64_t* row_nnz,
                  uint32_t* fields, uint32_t* indices, float* values,
                  int64_t max_rows, int64_t max_nnz,
                  int64_t* out_rows, int64_t* out_nnz) {
  return parse_libfm_impl<uint32_t>(data, len, labels, row_nnz, fields,
                                    indices, values, max_rows, max_nnz,
                                    out_rows, out_nnz);
}

// Parse dense CSV (no quoting — numeric data files): every line becomes
// ncols doubles; the first line fixes ncols. Outputs values row-major into
// out [max_rows * expect_cols]. If expect_cols == 0 it is inferred and
// written to *out_cols.
int parse_csv(const char* data, int64_t len, float* out,
              int64_t max_rows, int64_t expect_cols,
              int64_t* out_rows, int64_t* out_cols) {
  const char* p = data;
  const char* end = data + len;
  int64_t rows = 0;
  int64_t ncols = expect_cols;
  while (p != end) {
    while (p != end && is_eol(*p)) ++p;
    if (p == end) break;
    if (rows >= max_rows) return DMLC_TPU_EOVERFLOW;
    int64_t col = 0;
    float* row_out = out + rows * (ncols > 0 ? ncols : 0);
    for (;;) {
      double val = 0.0;
      while (p != end && is_space(*p)) ++p;
      if (p != end && *p != ',' && !is_eol(*p)) {
        const char* q = scan_double(p, end, &val);
        if (q == nullptr) return DMLC_TPU_EPARSE;
        p = q;
        while (p != end && is_space(*p)) ++p;
      }
      if (ncols > 0) {
        if (col >= ncols) return DMLC_TPU_EPARSE;
        row_out[col] = static_cast<float>(val);
      } else {
        // inference pass for first row: caller guarantees capacity via
        // max_rows * (commas in first line + 1)
        out[col] = static_cast<float>(val);
      }
      ++col;
      if (p == end || is_eol(*p)) {
        if (p != end) ++p;
        break;
      }
      if (*p != ',') return DMLC_TPU_EPARSE;
      ++p;
    }
    if (ncols <= 0) {
      ncols = col;
      row_out = out;
    } else if (col != ncols) {
      return DMLC_TPU_EPARSE;
    }
    ++rows;
  }
  *out_rows = rows;
  *out_cols = ncols;
  return DMLC_TPU_OK;
}

// One-pass upper-bound counter for output sizing: *out_rows = newline count
// + 1, *out_tokens = whitespace-delimited token count (>= nnz + rows).
void count_tokens(const char* data, int64_t len,
                  int64_t* out_rows, int64_t* out_tokens) {
  int64_t rows = 1, tokens = 0;
  bool in_tok = false;
  for (int64_t i = 0; i < len; ++i) {
    char c = data[i];
    if (is_eol(c)) {
      ++rows;
      in_tok = false;
    } else if (is_space(c)) {
      in_tok = false;
    } else if (!in_tok) {
      in_tok = true;
      ++tokens;
    }
  }
  *out_rows = rows;
  *out_tokens = tokens;
}

int dmlc_tpu_abi_version(void) { return DMLC_TPU_ABI_VERSION; }

// SIMD tier actually selected at runtime (CPUID + DMLC_TPU_SIMD gate):
// 0 = scalar, 2 = AVX2+BMI2 tokenizer engine. Exposed for telemetry and
// the parse-parity tests.
int dmlc_tpu_simd_level(void) { return SimdKernelLevel(); }

}  // extern "C"
