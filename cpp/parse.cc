// Native hot loops: text chunk -> CSR arrays.
//
// TPU-build equivalent of the reference's parse path (src/data/strtonum.h,
// libsvm_parser.h, libfm_parser.h, csv_parser.h): the chunk-level tokenize +
// numeric-convert loop is the ingest bottleneck, so it lives in C++ behind a
// flat C ABI (ctypes-loadable, zero Python objects inside). Design differs
// from the reference: single forward scan with branch-light inline float
// parsing, caller-allocated output arrays (upper bounds derived from the
// chunk), and row/nnz counts returned for exact trimming. No OpenMP — the
// Python side maps chunk pieces onto a thread pool and ctypes releases the
// GIL, so parallelism composes at the chunk level.

#include <cstdint>
#include <cstring>
#include <cmath>

namespace {

inline bool is_space(char c) { return c == ' ' || c == '\t'; }

// '\r' is a line terminator (LineSplitter record boundaries accept \n, \r,
// and \r\n), never inline whitespace — treating it as a space would merge
// adjacent rows.
inline bool is_eol(char c) { return c == '\n' || c == '\r'; }

inline bool is_digit(char c) { return c >= '0' && c <= '9'; }

// Fast float scan: sign, integer part, fraction, optional exponent.
// Handles the common data-file cases inline; no INF/NAN/hex (same contract
// as the reference's strtonum.h:37, by design: data files don't contain
// them, and rejecting keeps the loop branch-light).
inline const char* scan_double(const char* p, const char* end, double* out) {
  if (p == end) return nullptr;
  bool neg = false;
  if (*p == '-') { neg = true; ++p; }
  else if (*p == '+') { ++p; }
  if (p == end || (!is_digit(*p) && *p != '.')) return nullptr;
  double val = 0.0;
  while (p != end && is_digit(*p)) {
    val = val * 10.0 + (*p - '0');
    ++p;
  }
  if (p != end && *p == '.') {
    ++p;
    double scale = 0.1;
    while (p != end && is_digit(*p)) {
      val += (*p - '0') * scale;
      scale *= 0.1;
      ++p;
    }
  }
  if (p != end && (*p == 'e' || *p == 'E')) {
    ++p;
    bool eneg = false;
    if (p != end && (*p == '-' || *p == '+')) { eneg = (*p == '-'); ++p; }
    int ex = 0;
    while (p != end && is_digit(*p)) { ex = ex * 10 + (*p - '0'); ++p; }
    val *= std::pow(10.0, eneg ? -ex : ex);
  }
  *out = neg ? -val : val;
  return p;
}

inline const char* scan_u64(const char* p, const char* end, uint64_t* out) {
  if (p == end || !is_digit(*p)) return nullptr;
  uint64_t v = 0;
  while (p != end && is_digit(*p)) { v = v * 10 + (*p - '0'); ++p; }
  *out = v;
  return p;
}

}  // namespace

extern "C" {

// Status codes shared by all parsers.
enum {
  DMLC_TPU_OK = 0,
  DMLC_TPU_EOVERFLOW = -1,  // output capacity exceeded
  DMLC_TPU_EPARSE = -2,     // malformed input
};

// Feature flags reported by parse_libsvm.
enum {
  DMLC_TPU_HAS_WEIGHT = 1,
  DMLC_TPU_HAS_QID = 2,
  DMLC_TPU_HAS_VALUE = 4,
};

// Parse libsvm text: "label[:weight] [qid:n] idx[:val] ..." per line.
// Outputs: labels/weights [max_rows], qids [max_rows], row_nnz [max_rows],
// indices/values [max_nnz]. Rows with no explicit weight get 1.0; bare
// indices get value 1.0. Returns DMLC_TPU_OK/errors; *out_rows, *out_nnz,
// *out_flags are filled on success.
int parse_libsvm(const char* data, int64_t len,
                 float* labels, float* weights, int64_t* qids,
                 int64_t* row_nnz, uint64_t* indices, float* values,
                 int64_t max_rows, int64_t max_nnz,
                 int64_t* out_rows, int64_t* out_nnz, int* out_flags) {
  const char* p = data;
  const char* end = data + len;
  int64_t rows = 0, nnz = 0;
  int flags = 0;
  while (p != end) {
    while (p != end && (is_space(*p) || is_eol(*p))) ++p;
    if (p == end) break;
    // label [:weight]
    double label;
    const char* q = scan_double(p, end, &label);
    if (q == nullptr) return DMLC_TPU_EPARSE;
    p = q;
    double weight = 1.0;
    if (p != end && *p == ':') {
      ++p;
      q = scan_double(p, end, &weight);
      if (q == nullptr) return DMLC_TPU_EPARSE;
      p = q;
      flags |= DMLC_TPU_HAS_WEIGHT;
    }
    if (rows >= max_rows) return DMLC_TPU_EOVERFLOW;
    // missing qid -> 0, matching RowBlockContainer's neutral-default policy
    // (and the pure-Python twin)
    int64_t qid = 0;
    int64_t row_start = nnz;
    // features until newline
    for (;;) {
      while (p != end && is_space(*p)) ++p;
      if (p == end || is_eol(*p)) {
        if (p != end) ++p;
        break;
      }
      if (end - p > 4 && std::memcmp(p, "qid:", 4) == 0) {
        uint64_t qv;
        q = scan_u64(p + 4, end, &qv);
        if (q == nullptr) return DMLC_TPU_EPARSE;
        qid = static_cast<int64_t>(qv);
        flags |= DMLC_TPU_HAS_QID;
        p = q;
        continue;
      }
      uint64_t idx;
      q = scan_u64(p, end, &idx);
      if (q == nullptr) return DMLC_TPU_EPARSE;
      p = q;
      double val = 1.0;
      if (p != end && *p == ':') {
        ++p;
        q = scan_double(p, end, &val);
        if (q == nullptr) return DMLC_TPU_EPARSE;
        p = q;
        flags |= DMLC_TPU_HAS_VALUE;
      }
      if (nnz >= max_nnz) return DMLC_TPU_EOVERFLOW;
      indices[nnz] = idx;
      values[nnz] = static_cast<float>(val);
      ++nnz;
    }
    labels[rows] = static_cast<float>(label);
    weights[rows] = static_cast<float>(weight);
    qids[rows] = qid;
    row_nnz[rows] = nnz - row_start;
    ++rows;
  }
  *out_rows = rows;
  *out_nnz = nnz;
  *out_flags = flags;
  return DMLC_TPU_OK;
}

// Parse libfm text: "label field:idx:val ..." per line. Outputs as libsvm
// plus fields [max_nnz].
int parse_libfm(const char* data, int64_t len,
                float* labels, int64_t* row_nnz,
                uint64_t* fields, uint64_t* indices, float* values,
                int64_t max_rows, int64_t max_nnz,
                int64_t* out_rows, int64_t* out_nnz) {
  const char* p = data;
  const char* end = data + len;
  int64_t rows = 0, nnz = 0;
  while (p != end) {
    while (p != end && (is_space(*p) || is_eol(*p))) ++p;
    if (p == end) break;
    double label;
    const char* q = scan_double(p, end, &label);
    if (q == nullptr) return DMLC_TPU_EPARSE;
    p = q;
    if (rows >= max_rows) return DMLC_TPU_EOVERFLOW;
    int64_t row_start = nnz;
    for (;;) {
      while (p != end && is_space(*p)) ++p;
      if (p == end || is_eol(*p)) {
        if (p != end) ++p;
        break;
      }
      uint64_t field, idx;
      double val;
      q = scan_u64(p, end, &field);
      if (q == nullptr || q == end || *q != ':') return DMLC_TPU_EPARSE;
      q = scan_u64(q + 1, end, &idx);
      if (q == nullptr || q == end || *q != ':') return DMLC_TPU_EPARSE;
      q = scan_double(q + 1, end, &val);
      if (q == nullptr) return DMLC_TPU_EPARSE;
      p = q;
      if (nnz >= max_nnz) return DMLC_TPU_EOVERFLOW;
      fields[nnz] = field;
      indices[nnz] = idx;
      values[nnz] = static_cast<float>(val);
      ++nnz;
    }
    labels[rows] = static_cast<float>(label);
    row_nnz[rows] = nnz - row_start;
    ++rows;
  }
  *out_rows = rows;
  *out_nnz = nnz;
  return DMLC_TPU_OK;
}

// Parse dense CSV (no quoting — numeric data files): every line becomes
// ncols doubles; the first line fixes ncols. Outputs values row-major into
// out [max_rows * expect_cols]. If expect_cols == 0 it is inferred and
// written to *out_cols.
int parse_csv(const char* data, int64_t len, float* out,
              int64_t max_rows, int64_t expect_cols,
              int64_t* out_rows, int64_t* out_cols) {
  const char* p = data;
  const char* end = data + len;
  int64_t rows = 0;
  int64_t ncols = expect_cols;
  while (p != end) {
    while (p != end && is_eol(*p)) ++p;
    if (p == end) break;
    if (rows >= max_rows) return DMLC_TPU_EOVERFLOW;
    int64_t col = 0;
    float* row_out = out + rows * (ncols > 0 ? ncols : 0);
    for (;;) {
      double val = 0.0;
      while (p != end && is_space(*p)) ++p;
      if (p != end && *p != ',' && !is_eol(*p)) {
        const char* q = scan_double(p, end, &val);
        if (q == nullptr) return DMLC_TPU_EPARSE;
        p = q;
        while (p != end && is_space(*p)) ++p;
      }
      if (ncols > 0) {
        if (col >= ncols) return DMLC_TPU_EPARSE;
        row_out[col] = static_cast<float>(val);
      } else {
        // inference pass for first row: caller guarantees capacity via
        // max_rows * (commas in first line + 1)
        out[col] = static_cast<float>(val);
      }
      ++col;
      if (p == end || is_eol(*p)) {
        if (p != end) ++p;
        break;
      }
      if (*p != ',') return DMLC_TPU_EPARSE;
      ++p;
    }
    if (ncols <= 0) {
      ncols = col;
      row_out = out;
    } else if (col != ncols) {
      return DMLC_TPU_EPARSE;
    }
    ++rows;
  }
  *out_rows = rows;
  *out_cols = ncols;
  return DMLC_TPU_OK;
}

// One-pass upper-bound counter for output sizing: *out_rows = newline count
// + 1, *out_tokens = whitespace-delimited token count (>= nnz + rows).
void count_tokens(const char* data, int64_t len,
                  int64_t* out_rows, int64_t* out_tokens) {
  int64_t rows = 1, tokens = 0;
  bool in_tok = false;
  for (int64_t i = 0; i < len; ++i) {
    char c = data[i];
    if (is_eol(c)) {
      ++rows;
      in_tok = false;
    } else if (is_space(c)) {
      in_tok = false;
    } else if (!in_tok) {
      in_tok = true;
      ++tokens;
    }
  }
  *out_rows = rows;
  *out_tokens = tokens;
}

int dmlc_tpu_abi_version() { return 1; }

}  // extern "C"
