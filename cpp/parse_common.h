// Shared scalar parse primitives: the SWAR float/int scanners and the
// single-row LibSVM parser, used by both the portable scalar chunk loop
// (parse.cc) and the AVX2 tokenize+convert engine (parse_simd.cc). The SIMD
// engine falls back to ParseSvmRowScalar for any row it cannot prove it
// handles bit-identically (qid, exponents, tokens longer than its 8-byte
// window, malformed input), so the scalar row parser is the single source
// of truth for LibSVM semantics.
#ifndef DMLC_TPU_PARSE_COMMON_H_
#define DMLC_TPU_PARSE_COMMON_H_

#include <cstdint>
#include <cstring>

#include "dmlc_tpu.h"

namespace dmlc_tpu_parse {

inline bool is_space(char c) { return c == ' ' || c == '\t'; }

// '\r' is a line terminator (LineSplitter record boundaries accept \n, \r,
// and \r\n), never inline whitespace — treating it as a space would merge
// adjacent rows.
inline bool is_eol(char c) { return c == '\n' || c == '\r'; }

inline bool is_digit(char c) { return c >= '0' && c <= '9'; }

// Exact powers of ten: 10^k is representable exactly in a double for
// k <= 22, so mantissa*10^k / mantissa/10^k round once — the classic fast
// strtod fast path.
inline const double* Pow10Table() {
  static const double kPow10[23] = {1e0,  1e1,  1e2,  1e3,  1e4,  1e5,
                                    1e6,  1e7,  1e8,  1e9,  1e10, 1e11,
                                    1e12, 1e13, 1e14, 1e15, 1e16, 1e17,
                                    1e18, 1e19, 1e20, 1e21, 1e22};
  return kPow10;
}

inline double ApplyExp10(double val, int64_t exp10) {
  if (exp10 == 0) return val;
  const double* kPow10 = Pow10Table();
  // |exp10| beyond ±350 already saturates to ±inf / ±0 for any mantissa the
  // scan can produce (<= 1e19); clamping bounds the loop for adversarial
  // exponents like 1e-999999999. The clamp happens HERE, after the explicit
  // exponent has been folded in, so compensating pairs (long zero run +
  // large positive exponent) stay exact.
  if (exp10 > 350) exp10 = 350;
  else if (exp10 < -350) exp10 = -350;
  if (exp10 > 0) {
    while (exp10 > 22) { val *= 1e22; exp10 -= 22; }
    return val * kPow10[exp10];
  }
  exp10 = -exp10;
  while (exp10 > 22) { val /= 1e22; exp10 -= 22; }
  return val / kPow10[exp10];
}

// SWAR helpers for the fraction hot path: classify 8 bytes at once and
// convert a full 8-digit group with a multiply tree instead of a serial
// per-digit loop. `y` is the chunk XOR 0x30..30, so digit bytes are 0..9.
// Returns the count of leading (lowest-address-first) digit bytes and masks
// *digits down to them. Carry-free: the add is done on 7-bit bytes.
inline int CountDigits8(uint64_t y, uint64_t* digits) {
  uint64_t y7 = y & 0x7F7F7F7F7F7F7F7FULL;
  uint64_t nondigit =
      (((y7 + 0x7676767676767676ULL) | y) & 0x8080808080808080ULL);
  if (nondigit == 0) {
    *digits = y;
    return 8;
  }
  int k = __builtin_ctzll(nondigit) >> 3;
  *digits = y & ((1ULL << (k * 8)) - 1);
  return k;
}

// 8 ascii-stripped digit bytes (lowest address = most significant digit,
// little-endian load) -> the 8-digit number. Three multiplies total.
inline uint32_t Swar8Digits(uint64_t y) {
  const uint64_t mask = 0x000000FF000000FFULL;
  const uint64_t mul1 = 0x000F424000000064ULL;  // 100 + (1000000 << 32)
  const uint64_t mul2 = 0x0000271000000001ULL;  // 1 + (10000 << 32)
  y = (y * 10) + (y >> 8);
  return static_cast<uint32_t>(
      (((y & mask) * mul1) + (((y >> 16) & mask) * mul2)) >> 32);
}

// Fast float scan: sign, integer part, fraction, optional exponent.
// Handles the common data-file cases inline; no INF/NAN/hex (same contract
// as the reference's strtonum.h:37, by design: data files don't contain
// them, and rejecting keeps the loop branch-light). Digits accumulate into
// an integer mantissa (pipelinable integer ops, no serial FP chain); the
// decimal exponent is applied once at the end via exact powers of ten.
inline const char* scan_double(const char* p, const char* end, double* out) {
  if (p == end) return nullptr;
  bool neg = false;
  if (*p == '-') { neg = true; ++p; }
  else if (*p == '+') { ++p; }
  if (p == end || (!is_digit(*p) && *p != '.')) return nullptr;
  uint64_t mant = 0;
  int ndig = 0;   // significant digits folded into mant (19 max: fits uint64)
  // int64: bounded by the input length, so digit/zero runs can't overflow
  // it; saturation is applied once in ApplyExp10 after the explicit
  // exponent is added (a mid-scan cap would corrupt compensating pairs
  // like "0.<420 zeros>5e450").
  int64_t exp10 = 0;
  // ndig += (mant != 0) keeps leading zeros mantissa-budget-free without a
  // branch in the hot loop (folding a 0 into mant==0 is a numeric no-op).
  while (p != end && is_digit(*p)) {
    if (ndig < 19) {
      mant = mant * 10 + static_cast<uint64_t>(*p - '0');
      ndig += static_cast<int>(mant != 0);
    } else {
      ++exp10;
    }
    ++p;
  }
  if (p != end && *p == '.') {
    ++p;
    // 8-wide groups while the mantissa has room (mant*1e8 + 8 digits must
    // fit uint64: safe while ndig <= 11). A short group (k < 8) appends
    // 8-k virtual zero digits — value-preserving for a fraction tail, and
    // the byte at p+k is a real non-digit so the scalar loop below exits
    // immediately. An all-zero group before any significant digit shifts
    // the decimal point but costs no mantissa budget, so long zero runs
    // ("0.<420 zeros>5") skip 8 bytes at a time with their significant
    // digits preserved.
    while (end - p >= 8 && ndig <= 11) {
      uint64_t chunk;
      std::memcpy(&chunk, p, 8);
      uint64_t digs;
      int k = CountDigits8(chunk ^ 0x3030303030303030ULL, &digs);
      if (k == 0) break;
      // branchless: folding an all-zero group into a zero mantissa is a
      // numeric no-op, and ndig charges 8 only once a significant digit
      // has appeared
      mant = mant * 100000000ULL + Swar8Digits(digs);
      ndig += static_cast<int>(mant != 0) << 3;
      exp10 -= 8;
      p += k;
      if (k < 8) break;
    }
    while (p != end && is_digit(*p)) {
      if (ndig < 19) {
        mant = mant * 10 + static_cast<uint64_t>(*p - '0');
        ndig += static_cast<int>(mant != 0);
        --exp10;
      }
      ++p;
    }
  }
  if (p != end && (*p == 'e' || *p == 'E')) {
    ++p;
    bool eneg = false;
    if (p != end && (*p == '-' || *p == '+')) { eneg = (*p == '-'); ++p; }
    int ex = 0;
    while (p != end && is_digit(*p)) {
      if (ex < 100000000) ex = ex * 10 + (*p - '0');
      ++p;
    }
    exp10 += eneg ? -ex : ex;
  }
  *out = ApplyExp10(neg ? -static_cast<double>(mant)
                        : static_cast<double>(mant),
                    exp10);
  return p;
}

inline const char* scan_u64(const char* p, const char* end, uint64_t* out) {
  if (p == end || !is_digit(*p)) return nullptr;
  uint64_t v = 0;
  while (p != end && is_digit(*p)) { v = v * 10 + (*p - '0'); ++p; }
  *out = v;
  return p;
}

inline const uint64_t* Pow10U64Table() {
  static const uint64_t kPow10U64[9] = {1ULL,       10ULL,       100ULL,
                                        1000ULL,    10000ULL,    100000ULL,
                                        1000000ULL, 10000000ULL, 100000000ULL};
  return kPow10U64;
}

// SWAR u64 scan for LONG digit runs (high-cardinality feature ids: Criteo's
// 7-digit hashed ids). Classify 8 bytes at once, then convert the k leading
// digits in one multiply tree: the k digit bytes (most significant at the
// lowest address) are shifted up so Swar8Digits sees them as the LEAST
// significant digit positions behind leading zeros — value-exact, no
// division. ~constant ~20 ops per <=8-digit run vs a 4-5 cycle/digit serial
// mul-add chain; loses on 1-2 digit ids (measured 45% slower if applied
// unconditionally — see BASELINE.md round-3 notes), so callers pick it
// per-chunk from observed id lengths.
inline const char* scan_u64_swar(const char* p, const char* end,
                                 uint64_t* out) {
  if (p == end || !is_digit(*p)) return nullptr;
  const uint64_t* kPow10U64 = Pow10U64Table();
  uint64_t v = 0;
  while (end - p >= 8) {
    uint64_t chunk;
    std::memcpy(&chunk, p, 8);
    uint64_t digs;
    int k = CountDigits8(chunk ^ 0x3030303030303030ULL, &digs);
    if (k == 0) break;
    v = v * kPow10U64[k] + Swar8Digits(digs << ((8 - k) * 8));
    p += k;
    if (k < 8) { *out = v; return p; }
  }
  while (p != end && is_digit(*p)) { v = v * 10 + (*p - '0'); ++p; }
  *out = v;
  return p;
}

// Output cursor for a LibSVM parse: the caller-allocated arrays plus the
// running row/nnz counters and feature flags. Shared between the scalar
// chunk loop and the SIMD engine so fallback rows append seamlessly.
template <typename IndexT>
struct SvmSink {
  float* labels;
  float* weights;
  int64_t* qids;
  int64_t* row_nnz;
  IndexT* indices;
  float* values;
  int64_t max_rows;
  int64_t max_nnz;
  int64_t rows;
  int64_t nnz;
  int flags;
};

// Parse ONE LibSVM row: "label[:weight] [qid:n] idx[:val] ...". *pp must
// point at the first non-space byte of the row; on success it is advanced
// past the row's line terminator (one byte of \n or \r — the outer loop's
// space/eol skip absorbs the second byte of \r\n). When id_bytes/id_count
// are non-null (first row of a chunk) the feature-id byte lengths are
// sampled for the adaptive long-id scan selection.
template <typename IndexT>
inline int ParseSvmRowScalar(const char** pp, const char* end, bool long_ids,
                             int64_t* id_bytes, int64_t* id_count,
                             SvmSink<IndexT>* s) {
  const char* p = *pp;
  // label [:weight]
  double label;
  const char* q = scan_double(p, end, &label);
  if (q == nullptr) return DMLC_TPU_EPARSE;
  p = q;
  double weight = 1.0;
  if (p != end && *p == ':') {
    ++p;
    q = scan_double(p, end, &weight);
    if (q == nullptr) return DMLC_TPU_EPARSE;
    p = q;
    s->flags |= DMLC_TPU_HAS_WEIGHT;
  }
  if (s->rows >= s->max_rows) return DMLC_TPU_EOVERFLOW;
  // missing qid -> 0, matching RowBlockContainer's neutral-default policy
  // (and the pure-Python twin)
  int64_t qid = 0;
  int64_t row_start = s->nnz;
  // features until newline
  for (;;) {
    while (p != end && is_space(*p)) ++p;
    if (p == end || is_eol(*p)) {
      if (p != end) ++p;
      break;
    }
    if (*p == 'q' && end - p > 4 && std::memcmp(p, "qid:", 4) == 0) {
      uint64_t qv;
      q = scan_u64(p + 4, end, &qv);
      if (q == nullptr) return DMLC_TPU_EPARSE;
      qid = static_cast<int64_t>(qv);
      s->flags |= DMLC_TPU_HAS_QID;
      p = q;
      continue;
    }
    uint64_t idx;
    q = long_ids ? scan_u64_swar(p, end, &idx) : scan_u64(p, end, &idx);
    if (q == nullptr) return DMLC_TPU_EPARSE;
    if (id_bytes != nullptr) { *id_bytes += q - p; ++*id_count; }
    p = q;
    double val = 1.0;
    if (p != end && *p == ':') {
      ++p;
      q = scan_double(p, end, &val);
      if (q == nullptr) return DMLC_TPU_EPARSE;
      p = q;
      s->flags |= DMLC_TPU_HAS_VALUE;
    }
    if (s->nnz >= s->max_nnz) return DMLC_TPU_EOVERFLOW;
    s->indices[s->nnz] = static_cast<IndexT>(idx);
    s->values[s->nnz] = static_cast<float>(val);
    ++s->nnz;
  }
  s->labels[s->rows] = static_cast<float>(label);
  s->weights[s->rows] = static_cast<float>(weight);
  s->qids[s->rows] = qid;
  s->row_nnz[s->rows] = s->nnz - row_start;
  ++s->rows;
  *pp = p;
  return DMLC_TPU_OK;
}

// SIMD engine entry points (parse_simd.cc). SimdKernelLevel() reports the
// selected tier after the runtime CPUID check and the DMLC_TPU_SIMD env
// gate: 0 = scalar only, 2 = AVX2+BMI2 engine. The ParseSvmSimd* calls are
// only valid when the level is >= 2.
int SimdKernelLevel();
// true iff DMLC_TPU_SIMD=1 was set explicitly: skip the per-chunk shape
// probe and always dispatch to the engine (parity tests force it this way)
bool SimdKernelForced();
int ParseSvmSimdU32(const char* data, int64_t len, SvmSink<uint32_t>* s);
int ParseSvmSimdU64(const char* data, int64_t len, SvmSink<uint64_t>* s);

inline int ParseSvmSimd(const char* data, int64_t len, SvmSink<uint32_t>* s) {
  return ParseSvmSimdU32(data, len, s);
}
inline int ParseSvmSimd(const char* data, int64_t len, SvmSink<uint64_t>* s) {
  return ParseSvmSimdU64(data, len, s);
}

}  // namespace dmlc_tpu_parse

#endif  // DMLC_TPU_PARSE_COMMON_H_
