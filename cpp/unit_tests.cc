// Native unit tier: plain-assert tests of the C ABI, no framework.
//
// The reference's gtest tier (test/unittest/*.cc, one dmlc_unittest binary)
// covers its C++ library directly; this is the same tier for the native
// core — built and run by `make -C cpp test` and wired into pytest via
// tests/test_cpp_unit.py. The Python parity suite (tests/test_native.py)
// covers native-vs-Python agreement; this tier covers C++-only invariants
// (bounds, error codes, adversarial framing) without a Python interpreter
// in the loop.

#include <cassert>
#include <cinttypes>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <algorithm>
#include <cstring>
#include <string>
#include <vector>

// All ABI declarations come from the public header — definitions
// are compile-checked against it in every TU.
#include "dmlc_tpu.h"


namespace {

int g_checks = 0;

#define CHECK_TRUE(cond)                                                   \
  do {                                                                     \
    ++g_checks;                                                            \
    if (!(cond)) {                                                         \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      std::exit(1);                                                        \
    }                                                                      \
  } while (0)

bool near(double a, double b, double tol = 1e-6) {
  double d = a - b;
  if (d < 0) d = -d;
  double m = (a < 0 ? -a : a) + (b < 0 ? -b : b) + 1e-12;
  return d <= tol * m || d <= tol;
}

struct SvmOut {
  std::vector<float> labels, weights, values;
  std::vector<int64_t> qids, row_nnz;
  std::vector<uint64_t> indices;
  int64_t rows = 0, nnz = 0;
  int flags = 0;
  int rc = 0;
};

SvmOut run_libsvm(const std::string& text, int64_t cap = -1) {
  SvmOut o;
  int64_t bound = cap >= 0 ? cap : static_cast<int64_t>(text.size()) / 2 + 2;
  o.labels.resize(bound + 1);
  o.weights.resize(bound + 1);
  o.values.resize(bound + 1);
  o.qids.resize(bound + 1);
  o.row_nnz.resize(bound + 1);
  o.indices.resize(bound + 1);
  o.rc = parse_libsvm(text.data(), text.size(), o.labels.data(),
                      o.weights.data(), o.qids.data(), o.row_nnz.data(),
                      o.indices.data(), o.values.data(), bound, bound,
                      &o.rows, &o.nnz, &o.flags);
  return o;
}

void test_libsvm_basic() {
  SvmOut o = run_libsvm("1 1:0.5 7:2.25\n0:3.5 3:1e-3 4:-2.5e2\n");
  CHECK_TRUE(o.rc == 0);
  CHECK_TRUE(o.rows == 2 && o.nnz == 4);
  CHECK_TRUE(near(o.labels[0], 1.0) && near(o.labels[1], 0.0));
  CHECK_TRUE(near(o.weights[1], 3.5));  // label:weight form
  CHECK_TRUE(o.flags & 1);              // HAS_WEIGHT
  CHECK_TRUE(o.indices[1] == 7 && near(o.values[1], 2.25));
  CHECK_TRUE(near(o.values[2], 1e-3) && near(o.values[3], -250.0));
}

void test_libsvm_qid_and_bare() {
  SvmOut o = run_libsvm("2 qid:42 3 5\n");
  CHECK_TRUE(o.rc == 0 && o.rows == 1 && o.nnz == 2);
  CHECK_TRUE(o.qids[0] == 42 && (o.flags & 2));
  CHECK_TRUE(near(o.values[0], 1.0) && near(o.values[1], 1.0));  // bare idx
}

void test_libsvm_errors() {
  CHECK_TRUE(run_libsvm("not_a_number 1:2\n").rc == -2);  // EPARSE
  CHECK_TRUE(run_libsvm("1 1:0.5\n0 2:1.5\n", 1).rc == -1);  // EOVERFLOW
}

void test_libsvm_numeric_edges() {
  SvmOut o = run_libsvm(
      "1 1:0.000000000000000000123 2:1e-999999999 3:0." +
      std::string(420, '0') + "5e450 4:2e999999999\n");
  CHECK_TRUE(o.rc == 0 && o.nnz == 4);
  CHECK_TRUE(o.values[0] > 0.0f);                 // leading zeros kept
  CHECK_TRUE(o.values[1] == 0.0f);                // saturates to 0
  CHECK_TRUE(near(o.values[2], 5e29, 1e-3));      // compensating exponent
  CHECK_TRUE(o.values[3] > 1e30f && o.values[3] > 0);  // +inf
}

void test_libfm() {
  std::vector<float> labels(8), values(8);
  std::vector<uint64_t> fields(8), indices(8);
  std::vector<int64_t> row_nnz(8);
  int64_t rows, nnz;
  std::string text = "1 0:1:0.5 3:7:2.5\n0 1:2:-1.5\n";
  int rc = parse_libfm(text.data(), text.size(), labels.data(),
                       row_nnz.data(), fields.data(), indices.data(),
                       values.data(), 8, 8, &rows, &nnz);
  CHECK_TRUE(rc == 0 && rows == 2 && nnz == 3);
  CHECK_TRUE(fields[1] == 3 && indices[1] == 7 && near(values[1], 2.5));
  std::string bad = "1 0:1\n";  // missing third component
  rc = parse_libfm(bad.data(), bad.size(), labels.data(), row_nnz.data(),
                   fields.data(), indices.data(), values.data(), 8, 8,
                   &rows, &nnz);
  CHECK_TRUE(rc == -2);
}

void test_csv() {
  std::vector<float> out(16);
  int64_t rows, cols;
  std::string text = "1,0.5,2.5\n0,1.5,-3.5\n";
  CHECK_TRUE(parse_csv(text.data(), text.size(), out.data(), 4, 3, &rows,
                       &cols) == 0);
  CHECK_TRUE(rows == 2 && cols == 3 && near(out[5], -3.5));
  // inferred column count + empty cells parse as 0
  std::string text2 = "1,,2\n3,4,\n";
  CHECK_TRUE(parse_csv(text2.data(), text2.size(), out.data(), 4, 0, &rows,
                       &cols) == 0);
  CHECK_TRUE(cols == 3 && near(out[1], 0.0) && near(out[5], 0.0));
  // ragged row is a parse error
  std::string text3 = "1,2,3\n4,5\n";
  CHECK_TRUE(parse_csv(text3.data(), text3.size(), out.data(), 4, 0, &rows,
                       &cols) == -2);
}

void test_count_tokens() {
  int64_t rows, tokens;
  std::string text = "a bb  ccc\ndd\n\n";
  count_tokens(text.data(), text.size(), &rows, &tokens);
  CHECK_TRUE(tokens == 4);
  CHECK_TRUE(rows >= 3);  // upper bound contract: rows >= real row count
}

void test_recordio_roundtrip() {
  // payload containing the magic word mid-record (the adversarial case of
  // test/recordio_test.cc)
  const uint32_t kMagic = 0xced7230a;
  std::string payload = "hello";
  payload.append(reinterpret_cast<const char*>(&kMagic), 4);
  payload += "world";
  std::vector<char> packed(recordio_pack_bound(payload.data(),
                                               payload.size()));
  int64_t packed_len =
      recordio_pack(payload.data(), payload.size(), packed.data());
  CHECK_TRUE(packed_len > 0 && packed_len % 4 == 0);
  std::vector<char> out_data(payload.size() + 64);
  std::vector<int64_t> offsets(4);
  int64_t nrec, datalen, consumed;
  CHECK_TRUE(recordio_unpack(packed.data(), packed_len, out_data.data(),
                             offsets.data(), &nrec, &datalen,
                             &consumed) == 0);
  CHECK_TRUE(nrec == 1 && consumed == packed_len);
  CHECK_TRUE(datalen == static_cast<int64_t>(payload.size()));
  CHECK_TRUE(std::memcmp(out_data.data(), payload.data(), payload.size()) ==
             0);
  CHECK_TRUE(recordio_find_head(packed.data(), packed_len, 0) == 0);
}

void test_pipeline_end_to_end() {
  // two files, three parts: exactly-once row coverage through the full
  // native pipeline (reader thread + workers + ordered queue)
  char dir_template[] = "/tmp/dmlc_tpu_unit_XXXXXX";
  CHECK_TRUE(mkdtemp(dir_template) != nullptr);
  std::string paths_blob;
  std::vector<int64_t> sizes;
  std::vector<std::string> paths;
  int row_id = 0;
  for (int f = 0; f < 2; ++f) {
    std::string path = std::string(dir_template) + "/part" +
                       std::to_string(f) + ".svm";
    std::string content;
    for (int i = 0; i < 57; ++i, ++row_id) {
      content += std::to_string(row_id % 2) + " 1:" +
                 std::to_string(row_id) + ".25 2:0.5\n";
    }
    FILE* fp = std::fopen(path.c_str(), "wb");
    CHECK_TRUE(fp != nullptr);
    CHECK_TRUE(std::fwrite(content.data(), 1, content.size(), fp) ==
               content.size());
    std::fclose(fp);
    paths.push_back(path);
    sizes.push_back(static_cast<int64_t>(content.size()));
  }
  for (const std::string& p : paths) {
    paths_blob += p;
    paths_blob.push_back('\0');
  }
  int64_t total_rows = 0;
  for (int part = 0; part < 3; ++part) {
    void* h = ingest_open(paths_blob.data(), sizes.data(), 2, /*libsvm=*/0,
                          part, 3, /*nthread=*/2, /*chunk=*/1 << 16,
                          /*capacity=*/4, 0);
    CHECK_TRUE(h != nullptr);
    for (;;) {
      int64_t rows, nnz, ncols;
      int32_t flags;
      int rc = ingest_peek(h, &rows, &nnz, &ncols, &flags);
      CHECK_TRUE(rc >= 0);
      if (rc == 0) break;
      std::vector<float> labels(rows), values(nnz);
      std::vector<int64_t> offsets(rows + 1);
      std::vector<uint32_t> indices(nnz);
      CHECK_TRUE(ingest_fetch(h, labels.data(), nullptr, nullptr,
                              offsets.data(), indices.data(), values.data(),
                              nullptr) == 1);
      CHECK_TRUE(offsets[rows] == nnz);
      total_rows += rows;
    }
    CHECK_TRUE(ingest_bytes_read(h) > 0);
    ingest_close(h);
  }
  CHECK_TRUE(total_rows == 114);  // every row in exactly one part
  for (const std::string& p : paths) std::remove(p.c_str());
  std::remove(dir_template);
}

void test_pipeline_early_close() {
  // tear the pipeline down while the reader and workers are mid-stream —
  // the cancellation path where lifetime races hide (run under TSan/ASan
  // by make test_tsan / test_asan)
  char dir_template[] = "/tmp/dmlc_tpu_unit_close_XXXXXX";
  CHECK_TRUE(mkdtemp(dir_template) != nullptr);
  std::string path = std::string(dir_template) + "/big.svm";
  std::string content;
  for (int i = 0; i < 20000; ++i) {
    content += std::to_string(i % 2) + " 1:0.125 2:0.5 3:0.75\n";
  }
  FILE* fp = std::fopen(path.c_str(), "wb");
  CHECK_TRUE(fp != nullptr);
  CHECK_TRUE(std::fwrite(content.data(), 1, content.size(), fp) ==
             content.size());
  std::fclose(fp);
  std::string blob = path;
  blob.push_back('\0');
  int64_t size = static_cast<int64_t>(content.size());
  for (int round = 0; round < 6; ++round) {
    void* h = ingest_open(blob.data(), &size, 1, 0, 0, 1, /*nthread=*/4,
                          /*chunk=*/1 << 14, /*capacity=*/2, 0);
    CHECK_TRUE(h != nullptr);
    // consume `round` blocks, then close with work still in flight
    for (int k = 0; k < round; ++k) {
      int64_t rows, nnz, ncols;
      int32_t flags;
      if (ingest_peek(h, &rows, &nnz, &ncols, &flags) != 1) break;
      std::vector<float> labels(rows), values(nnz);
      std::vector<int64_t> offsets(rows + 1);
      std::vector<uint32_t> indices(nnz);
      CHECK_TRUE(ingest_fetch(h, labels.data(), nullptr, nullptr,
                              offsets.data(), indices.data(), values.data(),
                              nullptr) == 1);
    }
    ingest_close(h);
  }
  std::remove(path.c_str());
  std::remove(dir_template);
}

// Build one row-group payload (data/rowrec.py layout): labels f32[n],
// row_nnz u32[n] all = 1, indices u32[n] = 1, values f32[n].
std::string make_row_group(int base_label, int nrows, float value) {
  std::string p;
  p.push_back(0x52);  // tag
  p.push_back(4);     // flags: values
  p.push_back(0);
  p.push_back(0);
  uint32_t n = static_cast<uint32_t>(nrows);
  p.append(reinterpret_cast<const char*>(&n), 4);
  p.append(reinterpret_cast<const char*>(&n), 4);  // nnz == nrows
  for (int i = 0; i < nrows; ++i) {
    float lab = static_cast<float>((base_label + i) % 2);
    p.append(reinterpret_cast<const char*>(&lab), 4);
  }
  for (int i = 0; i < nrows; ++i) {
    uint32_t one = 1;
    p.append(reinterpret_cast<const char*>(&one), 4);
  }
  for (int i = 0; i < nrows; ++i) {
    uint32_t idx = 1;
    p.append(reinterpret_cast<const char*>(&idx), 4);
  }
  for (int i = 0; i < nrows; ++i) {
    p.append(reinterpret_cast<const char*>(&value), 4);
  }
  return p;
}

void test_pipeline_recordio_format() {
  // row-group records through the native pipeline at format=3, every
  // (part, nparts); values engineered to the magic bit pattern so payloads
  // carry aligned embedded magics (recordio_test.cc:17-47 adversarial)
  char dir_template[] = "/tmp/dmlc_tpu_unit_rio_XXXXXX";
  CHECK_TRUE(mkdtemp(dir_template) != nullptr);
  std::string path = std::string(dir_template) + "/rows.rec";
  float magic_value;
  uint32_t magic_bits = 0xced7230aU;
  std::memcpy(&magic_value, &magic_bits, 4);
  std::string framed;
  const int kGroups = 40, kRowsPer = 23;
  for (int g = 0; g < kGroups; ++g) {
    std::string payload = make_row_group(g * kRowsPer, kRowsPer, magic_value);
    std::string out(recordio_pack_bound(payload.data(), payload.size()), 0);
    int64_t wrote = recordio_pack(payload.data(), payload.size(), &out[0]);
    CHECK_TRUE(wrote > 0);
    framed.append(out.data(), wrote);
  }
  FILE* fp = std::fopen(path.c_str(), "wb");
  CHECK_TRUE(fp != nullptr);
  CHECK_TRUE(std::fwrite(framed.data(), 1, framed.size(), fp) ==
             framed.size());
  std::fclose(fp);
  std::string blob = path;
  blob.push_back('\0');
  int64_t size = static_cast<int64_t>(framed.size());
  for (int nparts : {1, 2, 3, 7}) {
    int64_t total_rows = 0;
    for (int part = 0; part < nparts; ++part) {
      void* h = ingest_open(blob.data(), &size, 1, /*recordio=*/3, part,
                            nparts, /*nthread=*/2, /*chunk=*/1 << 12,
                            /*capacity=*/4, 0);
      CHECK_TRUE(h != nullptr);
      for (;;) {
        int64_t rows, nnz, ncols;
        int32_t flags;
        int rc = ingest_peek(h, &rows, &nnz, &ncols, &flags);
        CHECK_TRUE(rc >= 0);
        if (rc == 0) break;
        CHECK_TRUE(nnz == rows);
        std::vector<float> labels(rows), values(nnz);
        std::vector<int64_t> offsets(rows + 1);
        std::vector<uint32_t> indices(nnz);
        CHECK_TRUE(ingest_fetch(h, labels.data(), nullptr, nullptr,
                                offsets.data(), indices.data(), values.data(),
                                nullptr) == 1);
        for (int64_t i = 0; i < nnz; ++i) {
          uint32_t bits;
          std::memcpy(&bits, &values[i], 4);
          CHECK_TRUE(bits == magic_bits);
          CHECK_TRUE(indices[i] == 1);
        }
        total_rows += rows;
      }
      ingest_close(h);
    }
    CHECK_TRUE(total_rows == kGroups * kRowsPer);
  }
  std::remove(path.c_str());
  std::remove(dir_template);
}

void test_pipeline_batch_staging() {
  // fixed-shape batch fetch: dense fill + COO fill agree with the row
  // stream, partial blocks carry across batches, staging survives close
  // with rows still staged
  char dir_template[] = "/tmp/dmlc_tpu_unit_batch_XXXXXX";
  CHECK_TRUE(mkdtemp(dir_template) != nullptr);
  std::string path = std::string(dir_template) + "/b.svm";
  std::string content;
  const int kRows = 1003;  // not a multiple of the batch size
  for (int i = 0; i < kRows; ++i) {
    content += std::to_string(i % 2) + " 1:" + std::to_string(i) +
               ".5 3:0.25\n";
  }
  FILE* fp = std::fopen(path.c_str(), "wb");
  CHECK_TRUE(fp != nullptr);
  CHECK_TRUE(std::fwrite(content.data(), 1, content.size(), fp) ==
             content.size());
  std::fclose(fp);
  std::string blob = path;
  blob.push_back('\0');
  int64_t size = static_cast<int64_t>(content.size());

  // dense sweep
  void* h = ingest_open(blob.data(), &size, 1, 0, 0, 1, /*nthread=*/2,
                        /*chunk=*/1 << 14, /*capacity=*/4, 0);
  CHECK_TRUE(h != nullptr);
  const int64_t kBatch = 128, kFeat = 5;
  std::vector<float> x(kBatch * kFeat), labels(kBatch), weights(kBatch);
  int64_t seen = 0;
  for (;;) {
    int64_t rows, nnz;
    int rc = ingest_stage_batch(h, kBatch, &rows, &nnz);
    CHECK_TRUE(rc >= 0);
    if (rc == 0) break;
    CHECK_TRUE(nnz == rows * 2);
    int64_t got = ingest_fetch_batch_dense(h, x.data(), labels.data(),
                                           weights.data(), kBatch, kFeat);
    CHECK_TRUE(got == rows);
    for (int64_t i = 0; i < got; ++i) {
      int64_t row_id = seen + i;
      CHECK_TRUE(labels[i] == static_cast<float>(row_id % 2));
      CHECK_TRUE(weights[i] == 1.0f);
      CHECK_TRUE(x[i * kFeat + 1] == static_cast<float>(row_id) + 0.5f);
      CHECK_TRUE(x[i * kFeat + 3] == 0.25f);
      CHECK_TRUE(x[i * kFeat + 0] == 0.0f);
    }
    for (int64_t i = got; i < kBatch; ++i) CHECK_TRUE(weights[i] == 0.0f);
    seen += got;
  }
  CHECK_TRUE(seen == kRows);
  double stats[7] = {0};
  ingest_stats(h, stats, 7);
  CHECK_TRUE(stats[0] == static_cast<double>(content.size()));
  CHECK_TRUE(stats[4] > 0);  // parse_ns
  ingest_close(h);

  // COO sweep with an overflow probe, then close mid-stage
  h = ingest_open(blob.data(), &size, 1, 0, 0, 1, 2, 1 << 14, 4, 0);
  CHECK_TRUE(h != nullptr);
  int64_t rows, nnz;
  CHECK_TRUE(ingest_stage_batch(h, 100, &rows, &nnz) == 1);
  CHECK_TRUE(rows == 100 && nnz == 200);
  std::vector<int32_t> idx(256), row_ids(256), offs(101);
  std::vector<float> vals(256);
  // bucket too small: fails without consuming
  CHECK_TRUE(ingest_fetch_batch_coo(h, labels.data(), weights.data(),
                                    idx.data(), vals.data(), row_ids.data(),
                                    offs.data(), 100, 100) < 0);
  CHECK_TRUE(ingest_fetch_batch_coo(h, labels.data(), weights.data(),
                                    idx.data(), vals.data(), row_ids.data(),
                                    offs.data(), 100, 256) == 100);
  CHECK_TRUE(idx[0] == 1 && idx[1] == 3 && row_ids[2] == 1);
  // CSR offsets mirror row_ids: offsets[r] <= e < offsets[r+1] iff
  // row_ids[e] == r; final offset = valid nnz
  CHECK_TRUE(offs[0] == 0 && offs[100] == 200);
  for (int e = 0; e < 200; ++e) {
    int r = row_ids[e];
    CHECK_TRUE(offs[r] <= e && e < offs[r + 1]);
  }
  for (int k = 200; k < 256; ++k) CHECK_TRUE(vals[k] == 0.0f);
  CHECK_TRUE(ingest_stage_batch(h, 4096, &rows, &nnz) == 1);  // stage rest
  ingest_close(h);  // staged blocks must be freed (ASan tier checks)

  std::remove(path.c_str());
  std::remove(dir_template);
}

void test_batch_coo_sharded() {
  // entries partitioned by destination shard with local row ids; padding
  // no-ops; overflow consumes nothing
  char dir_template[] = "/tmp/dmlc_tpu_unit_shard_XXXXXX";
  CHECK_TRUE(mkdtemp(dir_template) != nullptr);
  std::string path = std::string(dir_template) + "/s.svm";
  std::string content;
  const int kRows = 64;
  for (int i = 0; i < kRows; ++i) {
    // row i has (i % 3) + 1 entries at features 1..
    std::string line = std::to_string(i % 2);
    for (int k = 0; k <= i % 3; ++k) {
      line += " " + std::to_string(k + 1) + ":" + std::to_string(i) + ".25";
    }
    content += line + "\n";
  }
  FILE* fp = std::fopen(path.c_str(), "wb");
  CHECK_TRUE(fp != nullptr);
  CHECK_TRUE(std::fwrite(content.data(), 1, content.size(), fp) ==
             content.size());
  std::fclose(fp);
  std::string blob = path;
  blob.push_back('\0');
  int64_t size = static_cast<int64_t>(content.size());
  void* h = ingest_open(blob.data(), &size, 1, 0, 0, 1, 2, 1 << 14, 4, 0);
  CHECK_TRUE(h != nullptr);
  int64_t rows, nnz;
  CHECK_TRUE(ingest_stage_batch(h, kRows, &rows, &nnz) == 1);
  CHECK_TRUE(rows == kRows);
  const int64_t kShards = 4, kRowsPer = kRows / kShards;
  int64_t max_shard = ingest_staged_max_shard_nnz(h, kRows, kShards);
  CHECK_TRUE(max_shard > 0 && max_shard < nnz);
  // undersized bucket: fails without consuming
  std::vector<float> labels(kRows), weights(kRows);
  {
    std::vector<int32_t> idx(kShards * (max_shard - 1));
    std::vector<float> vals(kShards * (max_shard - 1));
    std::vector<int32_t> rid(kShards * (max_shard - 1));
    std::vector<int32_t> off(kShards * (kRowsPer + 1));
    CHECK_TRUE(ingest_fetch_batch_coo_sharded(
                   h, labels.data(), weights.data(), idx.data(), vals.data(),
                   rid.data(), off.data(), kRows, kShards,
                   max_shard - 1) < 0);
  }
  int64_t bucket = max_shard;
  std::vector<int32_t> idx(kShards * bucket), rid(kShards * bucket);
  std::vector<int32_t> offs(kShards * (kRowsPer + 1));
  std::vector<float> vals(kShards * bucket);
  CHECK_TRUE(ingest_fetch_batch_coo_sharded(
                 h, labels.data(), weights.data(), idx.data(), vals.data(),
                 rid.data(), offs.data(), kRows, kShards, bucket) == kRows);
  // per-shard local offsets mirror the local row ids
  for (int64_t s = 0; s < kShards; ++s) {
    const int32_t* off = offs.data() + s * (kRowsPer + 1);
    CHECK_TRUE(off[0] == 0);
    for (int64_t e = 0; e < bucket; ++e) {
      if (vals[s * bucket + e] == 0.0f) continue;  // padding
      int32_t r = rid[s * bucket + e];
      CHECK_TRUE(off[r] <= e && e < off[r + 1]);
    }
  }
  // verify: every entry's value row matches its shard section + local id
  int64_t seen = 0;
  for (int64_t s = 0; s < kShards; ++s) {
    for (int64_t k = 0; k < bucket; ++k) {
      float v = vals[s * bucket + k];
      if (v == 0.0f) continue;  // padding
      int64_t global_row = s * kRowsPer + rid[s * bucket + k];
      CHECK_TRUE(v == static_cast<float>(global_row) + 0.25f);
      CHECK_TRUE(rid[s * bucket + k] >= 0 && rid[s * bucket + k] < kRowsPer);
      ++seen;
    }
  }
  CHECK_TRUE(seen == nnz);
  ingest_close(h);
  std::remove(path.c_str());
  std::remove(dir_template);
}

void test_push_reserve_commit() {
  // zero-copy push: write libsvm text into reserved tail space in odd-sized
  // slices, commit, and drain — row coverage must be exact
  void* h = ingest_open_push(/*libsvm=*/0, /*nthread=*/2, /*chunk=*/1 << 14,
                             /*capacity=*/4, 0);
  CHECK_TRUE(h != nullptr);
  const int kRows = 5000;
  std::string text;
  for (int i = 0; i < kRows; ++i) {
    text += std::to_string(i % 2) + " 1:" + std::to_string(i) + ".5\n";
  }
  int64_t off = 0;
  int64_t slice = 777;  // deliberately unaligned with chunk size
  while (off < static_cast<int64_t>(text.size())) {
    int64_t n = std::min<int64_t>(slice, text.size() - off);
    char* dst = static_cast<char*>(ingest_push_reserve(h, n));
    CHECK_TRUE(dst != nullptr);
    std::memcpy(dst, text.data() + off, n);
    CHECK_TRUE(ingest_push_commit(h, n) == 0);
    off += n;
    slice = slice * 3 % 4096 + 64;
  }
  CHECK_TRUE(ingest_push_eof(h) == 0);
  int64_t total = 0;
  for (;;) {
    int64_t rows, nnz, ncols;
    int32_t flags;
    int rc = ingest_peek(h, &rows, &nnz, &ncols, &flags);
    CHECK_TRUE(rc >= 0);
    if (rc == 0) break;
    std::vector<float> labels(rows), values(nnz);
    std::vector<int64_t> offsets(rows + 1);
    std::vector<uint32_t> indices(nnz);
    CHECK_TRUE(ingest_fetch(h, labels.data(), nullptr, nullptr,
                            offsets.data(), indices.data(), values.data(),
                            nullptr) == 1);
    total += rows;
  }
  CHECK_TRUE(total == kRows);
  ingest_close(h);
}

// ingest_drive_push: the C-consumer remote-ingest driver. The "transport"
// here is a memory buffer served through the fetch callback in short,
// varying slices (what a ranged-GET loop looks like to the pipeline).
struct FetchCtx {
  const std::string* text;
  int64_t slice = 777;
  bool fail_at_half = false;
};

int64_t MemFetch(void* vctx, int64_t offset, char* buf, int64_t len) {
  FetchCtx* ctx = static_cast<FetchCtx*>(vctx);
  int64_t total = static_cast<int64_t>(ctx->text->size());
  if (ctx->fail_at_half && offset >= total / 2) return -1;  // transport err
  if (offset >= total) return 0;  // end of stream
  int64_t n = std::min<int64_t>(len, total - offset);
  n = std::min<int64_t>(n, ctx->slice);  // short reads
  ctx->slice = ctx->slice * 3 % 4096 + 64;
  std::memcpy(buf, ctx->text->data() + offset, static_cast<size_t>(n));
  return n;
}

void test_drive_push() {
  const int kRows = 5000;
  std::string text;
  for (int i = 0; i < kRows; ++i) {
    text += std::to_string(i % 2) + " 1:" + std::to_string(i) + ".5\n";
  }
  // unknown-length mode (total = -1): the callback's 0 return ends it
  void* h = ingest_open_push(/*libsvm=*/0, /*nthread=*/2, /*chunk=*/1 << 14,
                             /*capacity=*/4, 0);
  CHECK_TRUE(h != nullptr);
  FetchCtx ctx{&text};
  CHECK_TRUE(ingest_drive_push(h, MemFetch, &ctx, -1, 1 << 12) == 0);
  int64_t total_rows = 0;
  for (;;) {
    int64_t rows, nnz, ncols;
    int32_t flags;
    int rc = ingest_peek(h, &rows, &nnz, &ncols, &flags);
    CHECK_TRUE(rc >= 0);
    if (rc == 0) break;
    std::vector<float> labels(rows), values(nnz);
    std::vector<int64_t> offsets(rows + 1);
    std::vector<uint32_t> indices(nnz);
    CHECK_TRUE(ingest_fetch(h, labels.data(), nullptr, nullptr,
                            offsets.data(), indices.data(), values.data(),
                            nullptr) == 1);
    total_rows += rows;
  }
  CHECK_TRUE(total_rows == kRows);
  ingest_close(h);

  // transport failure mid-stream must abort the pipeline: the driver
  // returns an error and consumers see a failure, not a clean EOF
  void* h2 = ingest_open_push(0, 1, 1 << 14, 4, 0);
  CHECK_TRUE(h2 != nullptr);
  FetchCtx bad{&text};
  bad.fail_at_half = true;
  CHECK_TRUE(ingest_drive_push(h2, MemFetch, &bad, -1, 1 << 12) < 0);
  int64_t rows, nnz, ncols;
  int32_t flags;
  CHECK_TRUE(ingest_peek(h2, &rows, &nnz, &ncols, &flags) < 0);
  ingest_close(h2);

  // premature EOF against a declared length (truncated object / short
  // body) must also fail, not deliver a clean-but-short stream
  void* h3 = ingest_open_push(0, 1, 1 << 14, 4, 0);
  CHECK_TRUE(h3 != nullptr);
  FetchCtx trunc{&text};
  CHECK_TRUE(ingest_drive_push(h3, MemFetch, &trunc,
                               static_cast<int64_t>(text.size()) * 2,
                               1 << 12) < 0);
  CHECK_TRUE(ingest_peek(h3, &rows, &nnz, &ncols, &flags) < 0);
  ingest_close(h3);
}

}  // namespace

// Deterministic structured fuzz of the chunk parsers (the adversarial
// counterpart of the strtonum fuzz harness, tools/strtonum.py): random
// bytes, bit-flipped valid records, token soup, and truncations. The value
// is in WHICH binary runs it — this same function executes under the
// ASan+UBSan and TSan tiers (make -C cpp test_asan/test_tsan), so every
// out-of-bounds read a malformed chunk could provoke is instrumented.
// Asserts only the parser CONTRACT: rc in {OK, EOVERFLOW, EPARSE} and
// in-bounds output counts; xorshift seed fixed for reproducibility.
void test_parser_fuzz() {
  uint64_t s = 0x9E3779B97F4A7C15ULL;
  auto next = [&s]() {
    s ^= s << 13;
    s ^= s >> 7;
    s ^= s << 17;
    return s;
  };
  const std::string base = "1 1:0.5 2:1.5\n0 3:2.5\n";
  const char* toks[] = {"1", ":", ".", "-", "e", "\n", " ", "qid:",
                        "99999999999999999999", "1e999999", "-.e-", "\r",
                        "0.00000000000000000000000000000001"};
  for (int it = 0; it < 3000; ++it) {
    std::string data;
    switch (it & 3) {
      case 0: {  // raw bytes
        int64_t n = static_cast<int64_t>(next() % 200);
        for (int64_t i = 0; i < n; ++i)
          data.push_back(static_cast<char>(next() & 0xFF));
        break;
      }
      case 1: {  // bit-flipped valid records
        data = base;
        for (int k = 0; k < 1 + static_cast<int>(next() % 7); ++k)
          data[next() % data.size()] = static_cast<char>(next() & 0xFF);
        break;
      }
      case 2: {  // token soup
        int n = 1 + static_cast<int>(next() % 50);
        for (int k = 0; k < n; ++k)
          data += toks[next() % (sizeof(toks) / sizeof(toks[0]))];
        break;
      }
      default:  // truncation
        data = base.substr(0, next() % (base.size() + 1));
    }
    int64_t bound = static_cast<int64_t>(data.size()) / 2 + 2;
    std::vector<float> labels(bound), weights(bound), values(bound);
    std::vector<int64_t> qids(bound), row_nnz(bound);
    std::vector<uint32_t> indices(bound), fields(bound);
    int64_t rows = -1, nnz = -1;
    int flags = 0;
    int rc = parse_libsvm32(data.data(), data.size(), labels.data(),
                            weights.data(), qids.data(), row_nnz.data(),
                            indices.data(), values.data(), bound, bound,
                            &rows, &nnz, &flags);
    CHECK_TRUE(rc == 0 || rc == -1 || rc == -2);
    if (rc == 0) CHECK_TRUE(rows >= 0 && rows <= bound && nnz >= 0 &&
                            nnz <= bound);
    rc = parse_libfm32(data.data(), data.size(), labels.data(),
                       row_nnz.data(), fields.data(), indices.data(),
                       values.data(), bound, bound, &rows, &nnz);
    CHECK_TRUE(rc == 0 || rc == -1 || rc == -2);
    if (rc == 0) CHECK_TRUE(rows >= 0 && rows <= bound && nnz >= 0 &&
                            nnz <= bound);
    // csv capacity contract: caller sizes out from the first line's comma
    // count (pipeline.cc ParseCsvChunk does the same before calling)
    int64_t commas = 0;
    for (char c : data) {
      if (c == '\n' || c == '\r') break;
      commas += (c == ',');
    }
    int64_t csv_rows = static_cast<int64_t>(data.size()) + 1;
    std::vector<float> csv_out(csv_rows * (commas + 2));
    int64_t cols = 0;
    rc = parse_csv(data.data(), data.size(), csv_out.data(),
                   csv_rows, 0, &rows, &cols);
    CHECK_TRUE(rc == 0 || rc == -1 || rc == -2);
    if (rc == 0) CHECK_TRUE(rows >= 0 && rows <= csv_rows && cols >= 0 &&
                            rows * cols <= static_cast<int64_t>(
                                csv_out.size()));
  }
}

void test_pipeline_shuffle_chunks() {
  // ingest_open_ex with a seed: chunk visit order is a seeded
  // permutation — deterministic per seed, exactly-once, and refused for
  // multi-file inputs (the streaming reader cannot reorder). Runs under
  // ASan/TSan via the sanitizer targets.
  char dir_template[] = "/tmp/dmlc_tpu_unit_shuf_XXXXXX";
  CHECK_TRUE(mkdtemp(dir_template) != nullptr);
  std::string path = std::string(dir_template) + "/s.svm";
  std::string content;
  for (int i = 0; i < 40000; ++i) {
    content += std::to_string(i % 2) + " 1:" + std::to_string(i) + ".0\n";
  }
  FILE* fp = std::fopen(path.c_str(), "wb");
  CHECK_TRUE(fp != nullptr);
  CHECK_TRUE(std::fwrite(content.data(), 1, content.size(), fp) ==
             content.size());
  std::fclose(fp);
  std::string blob = path;
  blob.push_back('\0');
  int64_t size = static_cast<int64_t>(content.size());

  auto run = [&](int64_t seed) {
    std::vector<float> order;
    void* h = ingest_open_ex(blob.data(), &size, 1, /*libsvm=*/0, 0, 1,
                             /*nthread=*/2, /*chunk=*/1 << 14,
                             /*capacity=*/4, 0, seed);
    CHECK_TRUE(h != nullptr);
    for (;;) {
      int64_t rows, nnz, ncols;
      int32_t flags;
      int rc = ingest_peek(h, &rows, &nnz, &ncols, &flags);
      CHECK_TRUE(rc >= 0);
      if (rc == 0) break;
      std::vector<float> labels(rows), values(nnz);
      std::vector<int64_t> offsets(rows + 1);
      std::vector<uint32_t> indices(nnz);
      CHECK_TRUE(ingest_fetch(h, labels.data(), nullptr, nullptr,
                              offsets.data(), indices.data(), values.data(),
                              nullptr) == 1);
      order.insert(order.end(), values.begin(), values.end());
    }
    ingest_close(h);
    return order;
  };

  std::vector<float> seq = run(-1);
  CHECK_TRUE(static_cast<int>(seq.size()) == 40000);
  for (int i = 0; i < 40000; ++i) CHECK_TRUE(seq[i] == (float)i);
  std::vector<float> s7 = run(7);
  std::vector<float> s7b = run(7);
  std::vector<float> s9 = run(9);
  CHECK_TRUE(s7 == s7b);   // deterministic per seed
  CHECK_TRUE(s7 != seq);   // actually shuffled
  CHECK_TRUE(s7 != s9);    // seed-sensitive
  std::vector<float> sorted7 = s7;
  std::sort(sorted7.begin(), sorted7.end());
  CHECK_TRUE(sorted7 == seq);  // exactly-once
  // multi-file shuffle request must be refused (NULL), not degraded
  std::string blob2 = blob;
  blob2 += path;
  blob2.push_back('\0');
  int64_t sizes2[2] = {size, size};
  CHECK_TRUE(ingest_open_ex(blob2.data(), sizes2, 2, 0, 0, 1, 2, 1 << 14,
                            4, 0, /*seed=*/3) == nullptr);
  std::remove(path.c_str());
  std::remove(dir_template);
}

int main() {
  CHECK_TRUE(dmlc_tpu_abi_version() >= 1);
  test_parser_fuzz();
  test_libsvm_basic();
  test_libsvm_qid_and_bare();
  test_libsvm_errors();
  test_libsvm_numeric_edges();
  test_libfm();
  test_csv();
  test_count_tokens();
  test_recordio_roundtrip();
  test_pipeline_end_to_end();
  test_pipeline_early_close();
  test_pipeline_batch_staging();
  test_pipeline_recordio_format();
  test_batch_coo_sharded();
  test_push_reserve_commit();
  test_drive_push();
  test_pipeline_shuffle_chunks();
  std::printf("cpp unit tests ok (%d checks)\n", g_checks);
  return 0;
}
