// AVX2 tokenize + batch-convert engine for the LibSVM hot path.
//
// The scalar loop in parse.cc walks the chunk byte by byte: ~25-30 cycles
// per "idx:val" pair, which caps chunk parse near 1 GB/s on one core. This
// engine restructures the work into three passes so the per-byte and
// per-token costs vectorize:
//
//   1. tokenize: 32 bytes per iteration — vpcmpeqb masks for \n \r ' ' \t
//      ':' classify every byte, and token start/end offsets fall out of
//      (sep << 1) boundary masks via tzcnt extraction. A '-' that sits
//      between a separator and a non-separator is treated as a separator
//      too (a sign), so token starts always point at the first digit and
//      the converter never sees signs; any other '-' stays inside its
//      token and fails digit validation, routing the row to the scalar
//      fallback. Newline offsets are extracted the same way for row
//      assembly.
//   2. convert: branchless and fully lane-parallel, four tokens per
//      iteration. One vpgatherqq pulls the four 8-byte windows; length
//      masks, dot removal (lowest-set-bit blend), dot position (vpsadbw
//      byte count), digit validation, the ascii->integer multiply tree
//      (vpmaddubsw / vpmaddwd / vpmuludq), and the 10^e divisor (vgatherpd
//      from an exact table) never leave the vector unit. The window is
//      left-aligned, so the packed integer is mant * 10^(8-ndig) and the
//      value is exactly mant8 / 10^(8-dp) with dp = min(dotpos, len); both
//      operands are exact doubles, so the single vdivpd rounds once —
//      bit-identical to the scalar scan_double/strtod fast path.
//   3. assemble: a scalar walk over the token stream builds rows (label,
//      optional :weight, idx[:val] features), checking structure with the
//      separator byte after each token and start adjacency across ':'.
//      Signs are recovered here: data[st-1] == '-' flips the converted
//      value, and the byte before the sign is required to be a true
//      separator so shapes like "--5" or a freestanding "-" can never
//      silently parse — they fall back to the scalar oracle.
//
// Anything outside the proven-exact shapes — tokens longer than the 8-byte
// window, exponents, qid:, inf/nan, malformed rows — falls back to
// ParseSvmRowScalar for THAT ROW, so the scalar parser remains the single
// source of truth and outputs are bit-identical in all cases. The engine
// is compiled for generic x86-64 with per-function target("avx2,bmi,bmi2,
// lzcnt") attributes and only runs after a CPUID check (SimdKernelLevel),
// so the .so stays loadable on baseline hardware.

#include <cstdint>
#include <cstdlib>
#include <cstring>

#include "dmlc_tpu.h"
#include "parse_common.h"

#if defined(__x86_64__) && (defined(__GNUC__) || defined(__clang__))
#define DMLC_TPU_SIMD_X86 1
#include <immintrin.h>
#else
#define DMLC_TPU_SIMD_X86 0
#endif

namespace dmlc_tpu_parse {

int SimdKernelLevel() {
  static const int level = [] {
    const char* e = std::getenv("DMLC_TPU_SIMD");
    if (e != nullptr && e[0] != '\0' && !(e[0] == '1' && e[1] == '\0')) {
      // any value other than unset/"" /"1" disables ("0" is the documented
      // spelling); there is only one SIMD tier so the knob is a gate
      return 0;
    }
#if DMLC_TPU_SIMD_X86
    if (__builtin_cpu_supports("avx2") && __builtin_cpu_supports("bmi") &&
        __builtin_cpu_supports("bmi2")) {
      return 2;
    }
#endif
    return 0;
  }();
  return level;
}

bool SimdKernelForced() {
  static const bool forced = [] {
    const char* e = std::getenv("DMLC_TPU_SIMD");
    return e != nullptr && e[0] == '1' && e[1] == '\0';
  }();
  return forced;
}

#if DMLC_TPU_SIMD_X86

namespace {

constexpr int kTileTokens = 4096;
constexpr int kTileEvents = kTileTokens * 2;
// extraction writes up to 32 events past the soft cap (one full block),
// and the convert loop reads a full group of four past ntok
constexpr int kTileSlack = 40;

constexpr uint8_t kBad = 1;  // token needs the scalar row fallback
constexpr uint8_t kDot = 4;  // contains '.'

// true separators (sign '-' is contextual and never one of these)
inline bool IsBaseSep(unsigned char c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == ':';
}

struct Tile {
  uint32_t pos[kTileEvents + kTileSlack];  // alternating start/end offsets
  uint32_t nl[kTileEvents + kTileSlack];   // \n and \r offsets
  double val[kTileTokens + kTileSlack];    // converted numeric value
  uint8_t info[kTileTokens + kTileSlack];  // kBad | kDot
};

Tile* GetTile() {
  // one tile per parse thread; POD so thread_local costs a TLS slot, and
  // the ~170 KB stays L2-resident across chunks
  static thread_local Tile tile;
  return &tile;
}

// Tokenize [off, len) into tile->pos / tile->nl until the tile fills or
// the chunk ends. *prev_sep carries boundary state across blocks and
// calls: bit 0 = previous byte was an effective separator, bit 1 = it was
// a base separator (3 at beginning-of-chunk; rewinds set 1, which is
// always safe — a mis-sighted sign only widens the scalar fallback).
// Returns the scan frontier: events are complete for every byte before it.
__attribute__((target("avx2,bmi,lzcnt")))
int64_t TokenizeTile(const char* data, int64_t len, int64_t off,
                     uint32_t* prev_sep, Tile* tile, int* out_ne,
                     int* out_nn) {
  const __m256i vnl = _mm256_set1_epi8('\n');
  const __m256i vcr = _mm256_set1_epi8('\r');
  const __m256i vsp = _mm256_set1_epi8(' ');
  const __m256i vtb = _mm256_set1_epi8('\t');
  const __m256i vco = _mm256_set1_epi8(':');
  const __m256i vmi = _mm256_set1_epi8('-');
  int ne = 0, nn = 0;
  uint32_t prev_eff = *prev_sep & 1u;
  uint32_t prev_base = (*prev_sep >> 1) & 1u;
  while (off < len && ne < kTileEvents && nn < kTileEvents) {
    uint32_t m_nl, m_base, m_mi;
    int64_t tail = len - off;
    if (tail >= 32) {
      __m256i v =
          _mm256_loadu_si256(reinterpret_cast<const __m256i*>(data + off));
      m_nl = static_cast<uint32_t>(_mm256_movemask_epi8(_mm256_or_si256(
          _mm256_cmpeq_epi8(v, vnl), _mm256_cmpeq_epi8(v, vcr))));
      uint32_t m_sp = static_cast<uint32_t>(_mm256_movemask_epi8(
          _mm256_or_si256(_mm256_cmpeq_epi8(v, vsp),
                          _mm256_cmpeq_epi8(v, vtb))));
      uint32_t m_co = static_cast<uint32_t>(
          _mm256_movemask_epi8(_mm256_cmpeq_epi8(v, vco)));
      m_mi = static_cast<uint32_t>(
          _mm256_movemask_epi8(_mm256_cmpeq_epi8(v, vmi)));
      m_base = m_nl | m_sp | m_co;
      tail = 32;
    } else {
      // pad the final partial block with '\n': separator bytes, so a token
      // running to end-of-chunk gets its end event at exactly `len`, and
      // the nl extraction below masks the padding out
      alignas(32) unsigned char buf[32];
      std::memset(buf, '\n', sizeof(buf));
      std::memcpy(buf, data + off, static_cast<size_t>(tail));
      __m256i v = _mm256_load_si256(reinterpret_cast<const __m256i*>(buf));
      m_nl = static_cast<uint32_t>(_mm256_movemask_epi8(_mm256_or_si256(
          _mm256_cmpeq_epi8(v, vnl), _mm256_cmpeq_epi8(v, vcr))));
      uint32_t m_sp = static_cast<uint32_t>(_mm256_movemask_epi8(
          _mm256_or_si256(_mm256_cmpeq_epi8(v, vsp),
                          _mm256_cmpeq_epi8(v, vtb))));
      uint32_t m_co = static_cast<uint32_t>(
          _mm256_movemask_epi8(_mm256_cmpeq_epi8(v, vco)));
      m_mi = static_cast<uint32_t>(
          _mm256_movemask_epi8(_mm256_cmpeq_epi8(v, vmi)));
      m_base = m_nl | m_sp | m_co;
      m_nl &= (tail == 32) ? ~0u : ((1u << tail) - 1u);
    }
    // a '-' is a sign (and thus an effective separator) iff the previous
    // byte is a base separator and the next byte is not; the next-block
    // byte is classified scalar so bit 31 needs no lookahead iteration
    uint32_t next_is_sep =
        (off + tail < len) ? (IsBaseSep(static_cast<unsigned char>(
                                  data[off + tail]))
                                  ? 1u
                                  : 0u)
                           : 1u;
    uint32_t nextsep = (m_base >> 1) | (next_is_sep << 31);
    uint32_t sign = m_mi & ((m_base << 1) | prev_base) & ~nextsep;
    uint32_t m_sep = m_base | sign;
    uint32_t nonsep = ~m_sep;
    uint32_t starts = nonsep & ((m_sep << 1) | prev_eff);
    uint32_t ends = m_sep & ((nonsep << 1) | (prev_eff ^ 1u));
    prev_eff = m_sep >> 31;
    prev_base = m_base >> 31;
    uint32_t base = static_cast<uint32_t>(off);
    uint32_t ev = starts | ends;
    while (ev != 0) {
      tile->pos[ne++] = base + static_cast<uint32_t>(_tzcnt_u32(ev));
      ev = _blsr_u32(ev);
    }
    while (m_nl != 0) {
      tile->nl[nn++] = base + static_cast<uint32_t>(_tzcnt_u32(m_nl));
      m_nl = _blsr_u32(m_nl);
    }
    off += tail;
  }
  *prev_sep = prev_eff | (prev_base << 1);
  *out_ne = ne;
  *out_nn = nn;
  return off;
}

// spread the low 4 bits of b into the low bit of 4 consecutive bytes
inline uint32_t SpreadNibble(uint32_t b) {
  return (b * 0x00204081u) & 0x01010101u;
}

// Convert tokens [0, ntok) in groups of four, branchlessly. Each token's
// 8-byte window (starts point at the first digit: the tokenizer stripped
// signs) is masked to its length, the dot byte is squeezed out with a
// lowest-set-bit blend, and the remaining ascii digits go through the
// multiply tree: the window is left-aligned, so the packed integer is
// mant * 10^(8-ndig) and the value is exactly mant8 / 10^(8-dp) with
// dp = min(dotpos, len) in [0, 8]. Both operands are exact doubles, so
// the single divide rounds once: identical bits to scan_double.
__attribute__((target("avx2,bmi,lzcnt")))
void ConvertTile(const char* data, int64_t len, Tile* tile, int64_t ntok) {
  if (ntok <= 0) return;
  // powtab[dp] = 10^(8-dp), every entry exact
  static const double powtab[9] = {1e8, 1e7, 1e6, 1e5, 1e4,
                                   1e3, 1e2, 1e1, 1e0};
  // pad the event array so the last group's idle lanes replay the final
  // real token (keeps the gather in-bounds and the lanes harmless)
  for (int k = 0; k < 8; k += 2) {
    tile->pos[2 * ntok + k] = tile->pos[2 * ntok - 2];
    tile->pos[2 * ntok + k + 1] = tile->pos[2 * ntok - 1];
  }
  const __m256i vone = _mm256_set1_epi64x(1);
  const __m256i veight = _mm256_set1_epi64x(8);
  const __m256i v30 = _mm256_set1_epi8(0x30);
  const __m256i vdotx = _mm256_set1_epi8(0x1E);  // '.' ^ 0x30
  const __m256i vnine = _mm256_set1_epi8(9);
  const __m256i v01 = _mm256_set1_epi8(1);
  const __m256i vzero = _mm256_setzero_si256();
  const __m256i vm10_1 = _mm256_set1_epi16(0x010A);       // bytes [10, 1]
  const __m256i vm100_1 = _mm256_set1_epi32(0x00010064);  // words [100, 1]
  const __m256i vm1e4 = _mm256_set1_epi64x(10000);
  const __m256i idx_even = _mm256_setr_epi32(0, 2, 4, 6, 1, 3, 5, 7);
  for (int64_t i = 0; i < ntok; i += 4) {
    int64_t lastr = (i + 3 < ntok) ? i + 3 : ntok - 1;
    if (static_cast<int64_t>(tile->pos[2 * lastr]) + 8 > len) {
      // tokens inside the chunk's final 8 bytes: the window gather would
      // over-read the mapping, so route their row(s) to the scalar oracle
      for (int k = 0; k < 4; ++k) {
        tile->val[i + k] = 0.0;
        tile->info[i + k] = kBad;
      }
      continue;
    }
    __m256i pv = _mm256_loadu_si256(
        reinterpret_cast<const __m256i*>(tile->pos + 2 * i));
    __m256i de = _mm256_permutevar8x32_epi32(pv, idx_even);
    __m128i st4 = _mm256_castsi256_si128(de);           // starts
    __m128i en4 = _mm256_extracti128_si256(de, 1);      // ends
    // four plain loads beat vpgatherqq here: the offsets are already hot
    // in L1 and the inserts pipeline with the mask math below
    uint64_t w0, w1, w2, w3;
    std::memcpy(&w0, data + tile->pos[2 * i], 8);
    std::memcpy(&w1, data + tile->pos[2 * i + 2], 8);
    std::memcpy(&w2, data + tile->pos[2 * i + 4], 8);
    std::memcpy(&w3, data + tile->pos[2 * i + 6], 8);
    __m256i w = _mm256_set_epi64x(static_cast<int64_t>(w3),
                                  static_cast<int64_t>(w2),
                                  static_cast<int64_t>(w1),
                                  static_cast<int64_t>(w0));
    __m256i L = _mm256_cvtepu32_epi64(_mm_sub_epi32(en4, st4));
    __m256i L8 = _mm256_slli_epi64(L, 3);
    // (1 << 8*len) - 1; len == 8 shifts by 64 -> sllv yields 0 -> all-ones
    __m256i lenbit = _mm256_sllv_epi64(vone, L8);
    __m256i lenmask = _mm256_sub_epi64(lenbit, vone);
    __m256i y = _mm256_and_si256(_mm256_xor_si256(w, v30), lenmask);
    // dot handling: lowest set bit of (dot-compare | length-bit) marks
    // min(dotpos, len); bytes below it form dlow, and vpsadbw counts them
    __m256i dcmp = _mm256_cmpeq_epi8(y, vdotx);
    __m256i dcmp2 = _mm256_or_si256(dcmp, lenbit);
    __m256i low = _mm256_and_si256(dcmp2, _mm256_sub_epi64(vzero, dcmp2));
    __m256i dlow = _mm256_sub_epi64(low, vone);
    __m256i dp = _mm256_sad_epu8(_mm256_and_si256(dlow, v01), vzero);
    __m256i nodot = _mm256_cmpeq_epi64(dcmp, vzero);
    // ndig = len - hasdot; ndig == 0 (".", or sign debris) is malformed
    __m256i shift2 = _mm256_sub_epi64(L8, _mm256_andnot_si256(nodot, veight));
    __m256i ndigmask =
        _mm256_sub_epi64(_mm256_sllv_epi64(vone, shift2), vone);
    // squeeze the dot byte out: bytes below it stay, bytes above shift down
    __m256i m = _mm256_and_si256(
        _mm256_or_si256(_mm256_and_si256(y, dlow),
                        _mm256_andnot_si256(dlow, _mm256_srli_epi64(y, 8))),
        ndigmask);
    // any byte > 9 => not a plain digit string (second dot, exponent, a
    // '-' inside the token, letters): scalar fallback for the row
    __m256i okdig = _mm256_cmpeq_epi64(_mm256_subs_epu8(m, vnine), vzero);
    __m256i bad = _mm256_or_si256(
        _mm256_or_si256(_mm256_cmpeq_epi64(shift2, vzero),
                        _mm256_cmpgt_epi64(L, veight)),
        _mm256_andnot_si256(okdig, _mm256_cmpeq_epi64(vzero, vzero)));
    // ascii digit pack: pairs *10+1, then *100+1, then *10000+1
    __m256i t1 = _mm256_maddubs_epi16(m, vm10_1);
    __m256i t2 = _mm256_madd_epi16(t1, vm100_1);
    __m256i mant = _mm256_add_epi64(_mm256_mul_epu32(t2, vm1e4),
                                    _mm256_srli_epi64(t2, 32));
    // mant < 1e8 < 2^31: pack the four u64 lanes to i32 and convert exactly
    __m256i sh = _mm256_shuffle_epi32(mant, _MM_SHUFFLE(2, 0, 2, 0));
    __m128i pk = _mm_unpacklo_epi64(_mm256_castsi256_si128(sh),
                                    _mm256_extracti128_si256(sh, 1));
    __m256d md = _mm256_cvtepi32_pd(pk);
    __m256d pw = _mm256_i64gather_pd(powtab, dp, 8);
    _mm256_storeu_pd(tile->val + i, _mm256_div_pd(md, pw));
    uint32_t bb = static_cast<uint32_t>(
        _mm256_movemask_pd(_mm256_castsi256_pd(bad)));
    uint32_t db = ~static_cast<uint32_t>(
                      _mm256_movemask_pd(_mm256_castsi256_pd(nodot))) &
                  0xFu;
    uint32_t iw = SpreadNibble(bb) * kBad | (SpreadNibble(db) * kDot);
    std::memcpy(tile->info + i, &iw, 4);
  }
}

template <typename IndexT>
int ParseSvmSimdImpl(const char* data, int64_t len, SvmSink<IndexT>* s) {
  Tile* tile = GetTile();
  int64_t off = 0;
  uint32_t prev_sep = 3;  // beginning-of-chunk: effective + base separator
  bool row_open = false;
  double label = 0.0, weight = 1.0;
  int64_t row_start_nnz = 0;
  int64_t row_begin = 0;   // byte offset of the open row's label token
  int64_t consumed = 0;    // bytes eaten by scalar fallback rows
  while (off < len) {
    int ne = 0, nn = 0;
    int64_t scan_end = TokenizeTile(data, len, off, &prev_sep, tile, &ne, &nn);
    int64_t resume = scan_end;
    if (scan_end < len) {
      // mid-chunk tile boundary: rewind a dangling token start, and hold
      // back a trailing ':'-terminated token so idx:val / label:weight
      // pairs never straddle tiles (the assembler looks ahead one token)
      if (ne & 1) {
        resume = tile->pos[ne - 1];
        --ne;
        prev_sep = 1;
      }
      if (ne >= 2 && data[tile->pos[ne - 1]] == ':') {
        resume = tile->pos[ne - 2];
        ne -= 2;
        prev_sep = 1;
      }
      while (nn > 0 && tile->nl[nn - 1] >= resume) --nn;
    } else if (ne & 1) {
      // chunk ends inside a token scanned by a full 32-byte block (no pad
      // byte to close it): synthesize the end event at end-of-chunk
      tile->pos[ne++] = static_cast<uint32_t>(len);
    }
    int64_t ntok = ne / 2;
    ConvertTile(data, len, tile, ntok);
    int nl_i = 0;
    for (int64_t t = 0; t < ntok; ++t) {
      uint32_t st = tile->pos[2 * t];
      if (static_cast<int64_t>(st) < consumed) continue;
      uint32_t en = tile->pos[2 * t + 1];
      bool brk = false;
      while (nl_i < nn && tile->nl[nl_i] < st) {
        ++nl_i;
        brk = true;
      }
      if (brk && row_open) {
        s->labels[s->rows] = static_cast<float>(label);
        s->weights[s->rows] = static_cast<float>(weight);
        s->qids[s->rows] = 0;
        s->row_nnz[s->rows] = s->nnz - row_start_nnz;
        ++s->rows;
        row_open = false;
      }
      uint8_t info = tile->info[t];
      bool colon = static_cast<int64_t>(en) < len && data[en] == ':';
      // sign recovery: the tokenizer classified data[st-1] == '-' as a
      // separator only in sign position; require a true separator (or
      // chunk start) before it so "--5" and friends cannot slip through
      bool neg = st > 0 && data[st - 1] == '-';
      bool fall = false;
      if (!row_open) {
        // ---- label token, opens a row ----
        if ((info & kBad) != 0 ||
            (neg && st >= 2 &&
             !IsBaseSep(static_cast<unsigned char>(data[st - 2])))) {
          fall = true;
        } else {
          row_begin = st - (neg ? 1 : 0);
          label = neg ? -tile->val[t] : tile->val[t];
          weight = 1.0;
          row_start_nnz = s->nnz;
          row_open = true;
          if (colon) {
            // label:weight — the weight token must be adjacent (one byte
            // after the ':', or two with a sign), clean, and not itself
            // ':'-terminated
            uint32_t wst = t + 1 < ntok ? tile->pos[2 * t + 2] : 0;
            bool wneg = t + 1 < ntok && wst == en + 2 && data[en + 1] == '-';
            if (t + 1 >= ntok || (wst != en + 1 && !wneg) ||
                (tile->info[t + 1] & kBad) != 0 ||
                (static_cast<int64_t>(tile->pos[2 * t + 3]) < len &&
                 data[tile->pos[2 * t + 3]] == ':')) {
              fall = true;
            } else {
              weight = wneg ? -tile->val[t + 1] : tile->val[t + 1];
              s->flags |= DMLC_TPU_HAS_WEIGHT;
              ++t;
            }
          }
          if (!fall && s->rows >= s->max_rows) return DMLC_TPU_EOVERFLOW;
        }
      } else if (info & kBad) {
        // qid:, letters, exponents, window-overflow tokens
        fall = true;
      } else if (colon) {
        // ---- idx:val ----
        uint32_t vst = t + 1 < ntok ? tile->pos[2 * t + 2] : 0;
        bool vneg = t + 1 < ntok && vst == en + 2 && data[en + 1] == '-';
        if ((info & kDot) != 0 || neg || t + 1 >= ntok ||
            (vst != en + 1 && !vneg) || (tile->info[t + 1] & kBad) != 0 ||
            (static_cast<int64_t>(tile->pos[2 * t + 3]) < len &&
             data[tile->pos[2 * t + 3]] == ':')) {
          fall = true;
        } else {
          if (s->nnz >= s->max_nnz) return DMLC_TPU_EOVERFLOW;
          // integer tokens convert to an exact integral double
          s->indices[s->nnz] = static_cast<IndexT>(
              static_cast<uint64_t>(static_cast<int64_t>(tile->val[t])));
          s->values[s->nnz] = static_cast<float>(
              vneg ? -tile->val[t + 1] : tile->val[t + 1]);
          ++s->nnz;
          s->flags |= DMLC_TPU_HAS_VALUE;
          ++t;
          // ---- fast pair loop ----
          // a feature row is a run of clean idx:val pairs; validate each
          // with one branchless predicate instead of re-entering the
          // general state machine (any miss — newline, sign debris, bad
          // token, capacity — drops back out with nothing consumed)
          uint32_t next_nl =
              nl_i < nn ? tile->nl[nl_i] : 0xFFFFFFFFu;
          int64_t u = t + 1;
          while (u + 1 < ntok) {
            uint32_t fst = tile->pos[2 * u];
            uint32_t fen = tile->pos[2 * u + 1];
            uint32_t fvs = tile->pos[2 * u + 2];
            uint32_t fve = tile->pos[2 * u + 3];
            uint16_t inf2;
            std::memcpy(&inf2, tile->info + u, 2);
            uint32_t fneg = data[fvs - 1] == '-';
            // idx byte: no flags at all; value byte: kDot is fine, kBad not
            bool ok = ((inf2 & (0xFFu | (static_cast<uint32_t>(kBad) << 8))) ==
                       0) &
                      (data[fen] == ':') &
                      (fvs == fen + 1 + fneg) & (data[fst - 1] != '-') &
                      (fst < next_nl) & (s->nnz < s->max_nnz);
            if (!ok) break;
            if (static_cast<int64_t>(fve) < len && data[fve] == ':') break;
            uint64_t vb;
            std::memcpy(&vb, tile->val + (u + 1), 8);
            vb ^= static_cast<uint64_t>(fneg) << 63;
            double fv;
            std::memcpy(&fv, &vb, 8);
            s->indices[s->nnz] = static_cast<IndexT>(
                static_cast<uint64_t>(static_cast<int64_t>(tile->val[u])));
            s->values[s->nnz] = static_cast<float>(fv);
            ++s->nnz;
            u += 2;
          }
          t = u - 1;
        }
      } else {
        // ---- bare idx (implicit value 1.0) ----
        if ((info & kDot) != 0 || neg) {
          fall = true;
        } else {
          if (s->nnz >= s->max_nnz) return DMLC_TPU_EOVERFLOW;
          s->indices[s->nnz] = static_cast<IndexT>(
              static_cast<uint64_t>(static_cast<int64_t>(tile->val[t])));
          s->values[s->nnz] = 1.0f;
          ++s->nnz;
        }
      }
      if (fall) {
        // rewind the open row and let the scalar oracle parse it whole;
        // it consumes through the row's line terminator
        if (row_open) s->nnz = row_start_nnz;
        int64_t rb = row_open ? row_begin
                              : static_cast<int64_t>(st) - (neg ? 1 : 0);
        row_open = false;
        const char* q = data + rb;
        int64_t idb = 0, idc = 0;
        bool first = s->rows == 0;
        int rc = ParseSvmRowScalar<IndexT>(&q, data + len, false,
                                           first ? &idb : nullptr,
                                           first ? &idc : nullptr, s);
        if (rc != DMLC_TPU_OK) return rc;
        consumed = q - data;
        while (nl_i < nn && tile->nl[nl_i] < consumed) ++nl_i;
      }
    }
    // newlines between the last token and the resume frontier close the row
    while (nl_i < nn && tile->nl[nl_i] < resume) {
      ++nl_i;
      if (row_open) {
        s->labels[s->rows] = static_cast<float>(label);
        s->weights[s->rows] = static_cast<float>(weight);
        s->qids[s->rows] = 0;
        s->row_nnz[s->rows] = s->nnz - row_start_nnz;
        ++s->rows;
        row_open = false;
      }
    }
    if (consumed > resume) {
      // a fallback row ran past the scan frontier; resume just after its
      // line terminator, which is a separator by definition
      off = consumed;
      prev_sep = 1;
    } else {
      off = resume;
    }
  }
  if (row_open) {
    s->labels[s->rows] = static_cast<float>(label);
    s->weights[s->rows] = static_cast<float>(weight);
    s->qids[s->rows] = 0;
    s->row_nnz[s->rows] = s->nnz - row_start_nnz;
    ++s->rows;
  }
  return DMLC_TPU_OK;
}

}  // namespace

int ParseSvmSimdU32(const char* data, int64_t len, SvmSink<uint32_t>* s) {
  return ParseSvmSimdImpl<uint32_t>(data, len, s);
}
int ParseSvmSimdU64(const char* data, int64_t len, SvmSink<uint64_t>* s) {
  return ParseSvmSimdImpl<uint64_t>(data, len, s);
}

#else  // !DMLC_TPU_SIMD_X86

int ParseSvmSimdU32(const char*, int64_t, SvmSink<uint32_t>*) {
  return DMLC_TPU_EPARSE;  // unreachable: SimdKernelLevel() == 0
}
int ParseSvmSimdU64(const char*, int64_t, SvmSink<uint64_t>*) {
  return DMLC_TPU_EPARSE;
}

#endif  // DMLC_TPU_SIMD_X86

}  // namespace dmlc_tpu_parse
