/* Public C ABI of libdmlc_tpu.so — the native core of the TPU rebuild.
 *
 * The reference ships libdmlc.a consumed by C++ programs (xgboost, mxnet);
 * this header is the equivalent consumable surface for the rebuilt native
 * layer: chunk parsers (strtonum.h/libsvm_parser.h analogs), the RecordIO
 * binary format (recordio.h), and the threaded ingest pipeline
 * (threadediter.h + input_split_base.cc + text_parser.h as ONE engine).
 * The Python package binds exactly these symbols via ctypes
 * (dmlc_tpu/native/__init__.py); C++ consumers can dlopen or link the .so
 * directly. Everything is plain C types — no C++ ABI exposure.
 *
 * Thread-safety: a pipeline handle may be fed (push_*) by one thread and
 * drained (peek/fetch/stage) by another; per-handle calls within each side
 * must be serialized by the caller. Parsers are pure functions.
 *
 * Check dmlc_tpu_abi_version() == DMLC_TPU_ABI_VERSION before use: the ABI
 * evolves with the package and the two always ship together.
 */
#ifndef DMLC_TPU_H_
#define DMLC_TPU_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define DMLC_TPU_ABI_VERSION 7

/* ---- status codes (parsers and pipeline) ------------------------------ */
enum {
  DMLC_TPU_OK = 0,
  DMLC_TPU_EOVERFLOW = -1, /* output capacity exceeded / bucket too small */
  DMLC_TPU_EPARSE = -2     /* malformed input */
};

/* Feature flags reported by parse_libsvm* / ingest_peek. */
enum {
  DMLC_TPU_HAS_WEIGHT = 1,
  DMLC_TPU_HAS_QID = 2,
  DMLC_TPU_HAS_VALUE = 4
};

/* Ingest formats (ingest_open / ingest_open_push). */
enum {
  DMLC_TPU_FORMAT_LIBSVM = 0,
  DMLC_TPU_FORMAT_LIBFM = 1,
  DMLC_TPU_FORMAT_CSV = 2,
  DMLC_TPU_FORMAT_RECORDIO = 3
};

int dmlc_tpu_abi_version(void);

/* SIMD tier selected at runtime for the LibSVM parse path (CPUID check +
 * the DMLC_TPU_SIMD env gate): 0 = portable scalar, 2 = AVX2+BMI2
 * tokenize/convert engine. Results are bit-identical at every tier; the
 * value is telemetry for bench records and the parse-parity tests. */
int dmlc_tpu_simd_level(void);

/* ---- chunk parsers (src/data/strtonum.h + libsvm/libfm/csv analogs) ---
 * One forward scan per chunk: caller allocates outputs using upper bounds
 * (rows, nnz <= len/2 + 2; or count_tokens for exact-ish sizing), parser
 * returns exact counts for trimming. row_nnz holds per-row entry counts
 * (prefix-sum to CSR offsets). The *32 variants write u32 indices directly
 * (device layout, no narrowing pass). */
int parse_libsvm(const char* data, int64_t len, float* labels, float* weights,
                 int64_t* qids, int64_t* row_nnz, uint64_t* indices,
                 float* values, int64_t max_rows, int64_t max_nnz,
                 int64_t* out_rows, int64_t* out_nnz, int* out_flags);
int parse_libsvm32(const char* data, int64_t len, float* labels,
                   float* weights, int64_t* qids, int64_t* row_nnz,
                   uint32_t* indices, float* values, int64_t max_rows,
                   int64_t max_nnz, int64_t* out_rows, int64_t* out_nnz,
                   int* out_flags);
int parse_libfm(const char* data, int64_t len, float* labels,
                int64_t* row_nnz, uint64_t* fields, uint64_t* indices,
                float* values, int64_t max_rows, int64_t max_nnz,
                int64_t* out_rows, int64_t* out_nnz);
int parse_libfm32(const char* data, int64_t len, float* labels,
                  int64_t* row_nnz, uint32_t* fields, uint32_t* indices,
                  float* values, int64_t max_rows, int64_t max_nnz,
                  int64_t* out_rows, int64_t* out_nnz);
/* expect_cols <= 0 infers the column count from the first row. */
int parse_csv(const char* data, int64_t len, float* out, int64_t max_rows,
              int64_t expect_cols, int64_t* out_rows, int64_t* out_cols);
/* Upper-bound counter for output sizing: newline count + 1 rows,
 * whitespace-delimited token count (>= nnz + rows). */
void count_tokens(const char* data, int64_t len, int64_t* out_rows,
                  int64_t* out_tokens);

/* ---- RecordIO binary format (recordio.h / src/recordio.cc analog) -----
 * Byte-identical on-disk format: [magic 0xced7230a][cflag|len][data][pad4],
 * embedded magics split records into multi-part groups (cflag 1/2/3). */
int64_t recordio_pack_bound(const char* data, int64_t len);
/* Returns bytes written, or -1 when len >= 2^29 (the length field). */
int64_t recordio_pack(const char* data, int64_t len, char* out);
int64_t recordio_pack_batch_bound(const char* data, const int64_t* offsets,
                                  int64_t n);
int64_t recordio_pack_batch(const char* data, const int64_t* offsets,
                            int64_t n, char* out);
/* Decode every whole record in buf; out_offsets gets nrec+1 entries,
 * out_consumed the bytes of complete records (a trailing partial record is
 * left for the caller's next buffer). */
int recordio_unpack(const char* buf, int64_t len, char* out_data,
                    int64_t* out_offsets, int64_t* out_nrec,
                    int64_t* out_datalen, int64_t* out_consumed);
/* First whole-record head at/after start (4-byte aligned magic with a
 * non-continuation cflag), or -1 — the SeekRecordBegin resync primitive. */
int64_t recordio_find_head(const char* buf, int64_t len, int64_t start);

/* ---- threaded ingest pipeline ----------------------------------------
 * reader thread -> parse worker pool -> ordered block queue, with chunk
 * recycling (the reference's ThreadedIter free-cell discipline). Two ways
 * in: ingest_open reads local files (paths = nfiles NUL-terminated strings
 * back to back; part/nparts = exactly-once byte-range sharding), and
 * ingest_open_push lets the caller stream bytes (remote readahead). Both
 * return NULL on bad arguments. */
void* ingest_open(const char* paths, const int64_t* sizes, int32_t nfiles,
                  int32_t format, int32_t part, int32_t nparts,
                  int32_t nthread, int64_t chunk_bytes, int32_t capacity,
                  int64_t csv_expect_cols);

/* ingest_open + seeded chunk-shuffled visit order (the reference's
 * input_split_shuffle.h semantic: sub-splits visited in random order per
 * epoch, here at chunk granularity). shuffle_seed < 0 = off (identical to
 * ingest_open). Requires the zero-copy mmap reader (single-file byte
 * range, local, DMLC_TPU_MMAP != 0): the streaming reader cannot reorder
 * without deadlocking its bounded queues, so an unsatisfiable request
 * returns NULL and the caller falls back to its host-side shuffle. */
void* ingest_open_ex(const char* paths, const int64_t* sizes, int32_t nfiles,
                     int32_t format, int32_t part, int32_t nparts,
                     int32_t nthread, int64_t chunk_bytes, int32_t capacity,
                     int64_t csv_expect_cols, int64_t shuffle_seed);
void* ingest_open_push(int32_t format, int32_t nthread, int64_t chunk_bytes,
                       int32_t capacity, int64_t csv_expect_cols);

/* Push-mode feeding. Copying push, or zero-copy reserve/commit (write up to
 * `want` bytes into the returned buffer, then commit the count — the buffer
 * is valid until the next push call). End with push_eof; on a fetch failure
 * push_abort fails the pipeline so blocked consumers wake with an error. */
int ingest_push(void* handle, const char* data, int64_t len);
void* ingest_push_reserve(void* handle, int64_t want);
int ingest_push_commit(void* handle, int64_t n);
int ingest_push_eof(void* handle);
void ingest_push_abort(void* handle);

/* Remote-ingest driver (ABI >= 5). Transport boundary, by design: this
 * library ships no HTTP/object-store client — the consumer brings the
 * transport (libcurl, an SDK, a socket; the Python package's s3://gs://
 * readahead is one such consumer) and the pipeline brings record-boundary
 * cutting, parse fan-out and ordered delivery. `fetch` is called serially
 * with the next byte offset and a destination INSIDE the pipeline's push
 * memory (readinto semantics — no staging copy); it returns the bytes
 * written (<= len), 0 at end of stream, or < 0 on a transport error
 * (which aborts the pipeline so blocked consumers fail fast instead of
 * hanging). `total` < 0 streams until fetch returns 0; `fetch_bytes`
 * <= 0 defaults to 1 MiB per call. On success the stream is EOF'd and
 * the handle drains through ingest_peek/fetch as usual. Returns 0 or a
 * pipeline error code. */
typedef int64_t (*dmlc_tpu_fetch_fn)(void* ctx, int64_t offset, char* buf,
                                     int64_t len);
int ingest_drive_push(void* handle, dmlc_tpu_fetch_fn fetch, void* ctx,
                      int64_t total, int64_t fetch_bytes);

/* Block-at-a-time draining: peek blocks for the next in-order parsed block
 * (1 = ready, 0 = end of stream, <0 = pipeline error) and reports sizes;
 * fetch copies it out (CSR: offsets[rows+1], u32 indices); fetch_view hands
 * out zero-copy pointers plus an owner token to release via block_free. */
int ingest_peek(void* handle, int64_t* rows, int64_t* nnz, int64_t* ncols,
                int32_t* flags);
int ingest_fetch(void* handle, float* labels, float* weights, int64_t* qids,
                 int64_t* offsets, uint32_t* indices, float* values,
                 uint32_t* fields);
void* ingest_fetch_view(void* handle, float** labels, float** weights,
                        int64_t** qids, int64_t** offsets, uint32_t** indices,
                        float** values, uint32_t** fields);
void ingest_block_free(void* block);

/* Fixed-shape batch staging (the TPU feed fast path): stage_batch gathers
 * the next batch_size rows (1 = staged, 0 = end of stream, <0 = error);
 * the matching fetch consumes them into device-layout buffers, padded to
 * static shapes (padding entries are arithmetic no-ops).
 *  - dense: x[batch, F] row-major, short batches zero-padded (weight 0)
 *  - coo: indices/values/row_ids[nnz_bucket] + CSR offsets[batch+1]
 *  - coo_sharded: flat [num_shards * nnz_bucket] per-shard entry sections
 *    with LOCAL row ids + offsets[num_shards * (batch/num_shards + 1)],
 *    so sharding the leading dim ships each device only its own entries.
 * Fetch returns rows consumed, or DMLC_TPU_EOVERFLOW (consuming nothing)
 * when a bucket is too small — staged_max_shard_nnz sizes it. */
int ingest_stage_batch(void* handle, int64_t batch_size, int64_t* rows,
                       int64_t* nnz);
int64_t ingest_fetch_batch_dense(void* handle, float* x, float* labels,
                                 float* weights, int64_t batch_size,
                                 int64_t num_features);
int64_t ingest_fetch_batch_coo(void* handle, float* labels, float* weights,
                               int32_t* indices, float* values,
                               int32_t* row_ids, int32_t* offsets,
                               int64_t batch_size, int64_t nnz_bucket);
int64_t ingest_staged_max_shard_nnz(void* handle, int64_t batch_size,
                                    int64_t num_shards);
int64_t ingest_fetch_batch_coo_sharded(void* handle, float* labels,
                                       float* weights, int32_t* indices,
                                       float* values, int32_t* row_ids,
                                       int32_t* offsets, int64_t batch_size,
                                       int64_t num_shards,
                                       int64_t nnz_bucket);

/* Telemetry: out[0]=bytes_read, [1]=chunks, [2]=reader_io_ns,
 * [3]=reader_wait_ns, [4]=parse_ns, [5]=worker_wait_ns,
 * [6]=consumer_wait_ns (SURVEY §5.1 per-stage timers). */
void ingest_stats(void* handle, double* out, int32_t n);
int64_t ingest_bytes_read(void* handle);
void ingest_close(void* handle);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* DMLC_TPU_H_ */
