#!/usr/bin/env python
"""Lint obs metric names against the naming rule and the docs.

Walks dmlc_tpu/ + bench.py for ``registry().counter("...")``-style
registrations (the obs API takes the metric name as the first literal
argument — a non-literal name is invisible to this lint and to readers,
so keep names literal at call sites) and fails when a name

- does not follow ``dmlc_<area>_<name>_<unit>`` with the unit suffix in
  UNITS (counters must end ``_total``), or
- is not documented in docs/observability.md (backticked), or
- is documented but no longer registered anywhere (stale docs).

Run directly (exit code 0/1) or via tests/test_metric_lint.py.
"""

from __future__ import annotations

import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parents[1]
DOC = ROOT / "docs" / "observability.md"

UNITS = {"total", "ns", "bytes", "rows", "value", "count", "rank", "version",
         "mbps",
         # compiled-step cost attribution (obs/xla_cost.py + goodput MFU):
         # per-call FLOPs, "bytes accessed" (XLA cost_analysis's own key,
         # kept verbatim), a 0..1 utilization ratio, sampled milliseconds
         "flops", "accessed", "ratio", "ms"}

# ".counter(" / ".gauge(" / ".histogram(" followed by a string literal —
# matches across the line break of a wrapped call
CALL_RE = re.compile(
    r"\.(counter|gauge|histogram)\(\s*[\"']([^\"']+)[\"']", re.S
)
# require a unit suffix so prose mentions of e.g. `dmlc_tpu.obs` don't
# read as metric names
DOC_NAME_RE = re.compile(
    r"`(dmlc_[a-z0-9_]+_"
    r"(?:total|ns|bytes|rows|value|count|rank|version|mbps"
    r"|flops|accessed|ratio|ms))"
)


def registered_names() -> dict:
    """name -> list of (relative path, kind) registration sites."""
    out: dict = {}
    files = sorted(ROOT.glob("dmlc_tpu/**/*.py")) + [ROOT / "bench.py"]
    for path in files:
        if "tests" in path.parts:
            continue
        for kind, name in CALL_RE.findall(path.read_text()):
            out.setdefault(name, []).append(
                (str(path.relative_to(ROOT)), kind)
            )
    return out


def documented_names() -> set:
    if not DOC.exists():
        return set()
    return set(DOC_NAME_RE.findall(DOC.read_text()))


def lint() -> list:
    errors = []
    names = registered_names()
    documented = documented_names()
    if not names:
        errors.append(
            "no metric registrations found under dmlc_tpu/ — the lint's "
            "call-site regex is probably out of sync with the obs API"
        )
    if not DOC.exists():
        errors.append(f"missing {DOC.relative_to(ROOT)}")
    for name, sites in sorted(names.items()):
        where = ", ".join(f"{p} ({k})" for p, k in sites[:3])
        segs = name.split("_")
        if not name.startswith("dmlc_"):
            errors.append(f"{name}: must start with dmlc_  [{where}]")
            continue
        if len(segs) < 3:
            errors.append(
                f"{name}: want dmlc_<area>_<name>_<unit>  [{where}]"
            )
            continue
        if segs[-1] not in UNITS:
            errors.append(
                f"{name}: unit suffix {segs[-1]!r} not in "
                f"{sorted(UNITS)}  [{where}]"
            )
        if any(kind == "counter" for _, kind in sites) and segs[-1] != "total":
            errors.append(
                f"{name}: counters must end _total  [{where}]"
            )
        if documented and name not in documented:
            errors.append(
                f"{name}: not documented in docs/observability.md  [{where}]"
            )
    for name in sorted(documented - set(names)):
        errors.append(
            f"{name}: documented in docs/observability.md but never "
            "registered in source"
        )
    return errors


def main() -> int:
    errors = lint()
    for err in errors:
        print(f"check_metric_names: {err}")
    if errors:
        print(f"check_metric_names: {len(errors)} error(s)")
        return 1
    print(
        f"check_metric_names: {len(registered_names())} metric name(s) OK"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
