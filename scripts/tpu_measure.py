#!/usr/bin/env python
"""One-command TPU measurement sweep — run when the chip/tunnel is up.

Captures every device-side number the host-only tiers cannot (the round-3
lesson: a dead tunnel cost the round its TPU evidence, BENCH_r03.json
`device_unavailable`). Each measurement runs in a FRESH subprocess with a
hard timeout, so one hang cannot take down the sweep, and partial results
survive to the artifact.

    python scripts/tpu_measure.py [--out DIR]

Artifacts (JSON) land in --out (default /tmp/dmlc_tpu_bench/tpu_sweep):
  bench.json        full bench.py line (headline + device tiers:
                    feed prefetch A/B, text/recordio/criteo ingest→SGD,
                    psum + bucket A/B, parity)
  pallas_flash.json pallas flash local kernel vs XLA attention at long T
  summary.json      probe result + per-step status

The driver's round-end bench captures the same tiers; this script exists
so a transient tunnel-up window ANY time during a round can be harvested
immediately and recorded in BASELINE.md.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PALLAS_SNIPPET = r"""
import json, time
import numpy as np
import jax
import jax.numpy as jnp
import sys
sys.path.insert(0, %(repo)r)
from dmlc_tpu.ops.sequence_parallel import full_attention, make_pallas_flash_local

# one JSON line PER ROW, flushed as measured: a compile hang at a later T
# (killed by the parent's timeout) must not discard completed rows
print(json.dumps({"device": jax.devices()[0].platform}), flush=True)
B, H, D = 1, 8, 128
flash = make_pallas_flash_local(causal=True)
for T in (1024, 4096, 8192, 16384):
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
               for _ in range(3))
    fl = jax.jit(flash)
    xla = jax.jit(lambda q, k, v: full_attention(q, k, v, causal=True))
    row = {"T": T}
    for name, fn in (("pallas_ms", fl), ("xla_ms", xla)):
        try:
            r = fn(q, k, v)
            jax.block_until_ready(r)
            t0 = time.perf_counter()
            for _ in range(5):
                r = fn(q, k, v)
            jax.block_until_ready(r)
            row[name] = round((time.perf_counter() - t0) / 5 * 1e3, 2)
        except Exception as err:
            row[name] = f"error: {err}"
    print(json.dumps(row), flush=True)
"""


def _run(name: str, argv, out_dir: str, timeout: int, env=None) -> dict:
    """Run one measurement subprocess; save every JSON line it printed
    (jsonl — partial output from a timed-out child still lands in the
    artifact, per the round-3 lesson)."""
    t0 = time.time()
    stdout = ""
    try:
        proc = subprocess.run(
            argv, capture_output=True, text=True, timeout=timeout,
            cwd=REPO, env={**os.environ, **(env or {})},
        )
        status = {"rc": proc.returncode, "secs": round(time.time() - t0, 1)}
        stdout = proc.stdout or ""
        if proc.returncode != 0:
            status["stderr_tail"] = (proc.stderr or "")[-500:]
    except subprocess.TimeoutExpired as err:
        status = {"rc": "timeout", "secs": timeout}
        stdout = (err.stdout or b"").decode("utf-8", "replace") \
            if isinstance(err.stdout, bytes) else (err.stdout or "")
    lines = [ln for ln in stdout.splitlines() if ln.startswith("{")]
    if lines:
        with open(os.path.join(out_dir, name + ".json"), "w") as fh:
            fh.write("\n".join(lines) + "\n")
        status["artifact"] = name + ".json"
    return status


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="/tmp/dmlc_tpu_bench/tpu_sweep")
    ap.add_argument("--probe-timeout", type=int, default=90)
    args = ap.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)

    summary = {"started": time.strftime("%Y-%m-%d %H:%M:%S"), "steps": {}}

    def finish(result: str, code: int) -> int:
        summary["result"] = result
        with open(os.path.join(args.out, "summary.json"), "w") as fh:
            json.dump(summary, fh, indent=1)
        print(json.dumps(summary))
        return code

    # bounded probe first (bench.py owns the tunnel-hang probe logic:
    # jax.devices() HANGS when the tunnel is down, and this script must
    # never wedge a shell that polls it)
    sys.path.insert(0, REPO)
    from bench import _device_backend_probe_once

    t0 = time.time()
    ok, note = _device_backend_probe_once(args.probe_timeout)
    summary["steps"]["probe"] = {
        "ok": ok, "note": note, "secs": round(time.time() - t0, 1)}
    if not ok:
        return finish("tunnel down; nothing measured", 1)

    summary["steps"]["bench"] = _run(
        "bench", [sys.executable, "bench.py"], args.out, 2400,
        env={"DMLC_TPU_BENCH_PROBE_ATTEMPTS": "2",
             # bench.py's stdout is now a compact summary; route the full
             # per-sweep record into the harvest dir so the embed path
             # (bench._load_latest_harvest) finds every device tier
             "DMLC_TPU_BENCH_DETAIL": os.path.join(
                 args.out, "bench_detail.json")},
    )
    summary["steps"]["pallas_flash"] = _run(
        "pallas_flash",
        [sys.executable, "-c", _PALLAS_SNIPPET % {"repo": REPO}],
        args.out, 1200,
    )
    all_ok = all(s.get("rc") == 0 for s in summary["steps"].values()
                 if "rc" in s)
    return finish("sweep complete" if all_ok else "partial", 0 if all_ok
                  else 1)


if __name__ == "__main__":
    sys.exit(main())
