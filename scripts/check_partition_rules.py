#!/usr/bin/env python
"""Lint the in-tree partition-rule tables against their param trees.

The SPMD contract (parallel/partition.py) is that parameter placement is
DATA: a ``(regex, PartitionSpec)`` table matched against pytree leaf
names. Two table bugs are silent at authoring time and expensive at run
time:

- a non-scalar leaf NO rule matches — ``match_partition_rules`` raises,
  but only once a step is actually built on a mesh (tests on the
  single-device path never notice);
- a leaf matched by MORE than one rule — first-match order becomes
  load-bearing, and a later table edit reorders placement without any
  error anywhere.

This lint walks every registered rule table with a representative
parameter template and fails on either. Every ``*_PARTITION_RULES``
table exported from ``dmlc_tpu.models`` must be registered in ``CASES``
below — an unregistered table fails the lint too (the same
discoverability contract as scripts/check_faultpoints.py).

Run directly (exit 0/1) or via tests/test_partition.py.
"""

from __future__ import annotations

import os
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_cases():
    import jax

    from dmlc_tpu.models.fm import FM_PARTITION_RULES, init_fm_params
    from dmlc_tpu.models.linear import (
        LINEAR_MP_PARTITION_RULES,
        LINEAR_PARTITION_RULES,
        init_linear_params,
    )

    # abstract templates: leaf NAMES and shapes are what the lint needs,
    # never device buffers
    linear_t = jax.eval_shape(lambda: init_linear_params(8))
    fm_t = jax.eval_shape(lambda: init_fm_params(8, 4))
    return (
        ("LINEAR_PARTITION_RULES", LINEAR_PARTITION_RULES, linear_t),
        ("LINEAR_MP_PARTITION_RULES", LINEAR_MP_PARTITION_RULES, linear_t),
        ("FM_PARTITION_RULES", FM_PARTITION_RULES, fm_t),
    )


def exported_tables() -> set:
    """Names of every *_PARTITION_RULES constant defined under
    dmlc_tpu/models — the set CASES must cover."""
    import re

    names = set()
    table_re = re.compile(r"^([A-Z0-9_]+_PARTITION_RULES)\s*=", re.M)
    for path in sorted((ROOT / "dmlc_tpu" / "models").glob("*.py")):
        names.update(table_re.findall(path.read_text()))
    return names


def run() -> int:
    from dmlc_tpu.parallel.partition import lint_partition_rules

    cases = build_cases()
    problems = []
    covered = {name for name, _, _ in cases}
    for missing in sorted(exported_tables() - covered):
        problems.append(
            f"{missing}: defined in dmlc_tpu/models but not registered in "
            "scripts/check_partition_rules.py CASES (unlinted table)"
        )
    for name, rules, template in cases:
        for issue in lint_partition_rules(rules, template):
            problems.append(f"{name}: {issue}")
    if problems:
        for p in problems:
            print(f"check_partition_rules: {p}", file=sys.stderr)
        return 1
    print(
        f"check_partition_rules: OK ({len(cases)} tables, every non-scalar "
        "leaf matches exactly one rule)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(run())
