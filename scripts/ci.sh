#!/usr/bin/env bash
# CI entry point (the reference's .travis.yml + scripts/travis/travis_script.sh
# role): one command that runs every tier the suite ships.
#
#   scripts/ci.sh            # lint + native (incl. sanitizers) + pytest + bench smoke
#   scripts/ci.sh quick      # lint + native unit + pytest (no sanitizers/bench)
#
# Exit non-zero on the first failing tier. CPU-only safe: jax tests run on a
# virtual device mesh (tests/conftest.py); the bench smoke prints its JSON
# line from whatever device exists.

set -euo pipefail
cd "$(dirname "$0")/.."

MODE="${1:-full}"

echo "== lint =="
python scripts/lint.py

echo "== native build + unit tier =="
make -C cpp
make -C cpp test

if [ "$MODE" = "full" ]; then
  echo "== native sanitizer tiers (ASan+UBSan, TSan) =="
  make -C cpp test_asan
  make -C cpp test_tsan
fi

echo "== python suite =="
python -m pytest tests/ -q -x

if [ "$MODE" = "full" ]; then
  echo "== bench smoke (one JSON line) =="
  # bound the device probe: CI asserts the bench MACHINERY (one parseable
  # line, every tier runs), not tunnel availability — the full-patience
  # probe belongs to driver/harvest runs
  DMLC_TPU_BENCH_PROBE_ATTEMPTS=1 DMLC_TPU_BENCH_PROBE_TIMEOUT=45 \
    python bench.py
fi

echo "CI OK"
