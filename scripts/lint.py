#!/usr/bin/env python
"""Repo lint (the reference's scripts/lint.py role, stdlib-only).

Python tier: per-file AST checks — syntax, unused imports, bare excepts,
tab indentation. C++ tier: g++ -fsyntax-only -Wall -Wextra -Werror over
cpp/*.cc. Exit non-zero on any finding.

Usage: python scripts/lint.py [paths...]   (default: the whole repo)
"""

from __future__ import annotations

import ast
import os
import subprocess
import sys
from typing import Iterator, List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "node_modules"}


def _py_files(roots: List[str]) -> Iterator[str]:
    for root in roots:
        if os.path.isfile(root):
            if root.endswith(".py"):
                yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
            for name in filenames:
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


class _ImportTracker(ast.NodeVisitor):
    def __init__(self):
        self.imported = {}  # name -> lineno
        self.used = set()

    def visit_Import(self, node):
        for alias in node.names:
            name = (alias.asname or alias.name).split(".")[0]
            self.imported[name] = node.lineno

    def visit_ImportFrom(self, node):
        if node.module == "__future__":
            return  # compiler directives, not names
        for alias in node.names:
            if alias.name == "*":
                continue
            self.imported[alias.asname or alias.name] = node.lineno

    def visit_Name(self, node):
        self.used.add(node.id)

    def visit_Attribute(self, node):
        self.generic_visit(node)


def lint_python(path: str) -> List[str]:
    problems = []
    with open(path, "rb") as fh:
        src = fh.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as err:
        return [f"{path}:{err.lineno}: syntax error: {err.msg}"]
    tracker = _ImportTracker()
    tracker.visit(tree)
    text = src.decode("utf-8", "replace")
    # names referenced anywhere (incl. inside strings for __all__ re-exports
    # and docstring references is too loose — use AST names + dunder-all)
    exported = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "__all__":
                    if isinstance(node.value, (ast.List, ast.Tuple)):
                        exported |= {
                            elt.value
                            for elt in node.value.elts
                            if isinstance(elt, ast.Constant)
                        }
    for name, lineno in sorted(tracker.imported.items()):
        if name in tracker.used or name in exported:
            continue
        if name.startswith("_"):
            continue
        # keep imports marked as deliberate side-effect registrations
        line = text.splitlines()[lineno - 1]
        if "noqa" in line:
            continue
        problems.append(f"{path}:{lineno}: unused import '{name}'")
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            problems.append(
                f"{path}:{node.lineno}: bare 'except:' (catch something)"
            )
    for i, line in enumerate(text.splitlines(), 1):
        if line.startswith("\t"):
            problems.append(f"{path}:{i}: tab indentation")
    return problems


def lint_cpp() -> List[str]:
    cpp_dir = os.path.join(REPO, "cpp")
    if not os.path.isdir(cpp_dir):
        return []
    sources = [
        os.path.join(cpp_dir, f)
        for f in sorted(os.listdir(cpp_dir))
        if f.endswith(".cc")
    ]
    if not sources:
        return []
    proc = subprocess.run(
        ["g++", "-std=c++17", "-fsyntax-only", "-Wall", "-Wextra",
         "-Werror"] + sources,
        capture_output=True, text=True,
    )
    if proc.returncode != 0:
        return [line for line in proc.stderr.splitlines() if line.strip()]
    return []


def main(argv: List[str]) -> int:
    roots = argv or [
        os.path.join(REPO, "dmlc_tpu"),
        os.path.join(REPO, "tests"),
        os.path.join(REPO, "examples"),
        os.path.join(REPO, "scripts"),
        os.path.join(REPO, "bench.py"),
        os.path.join(REPO, "__graft_entry__.py"),
    ]
    problems: List[str] = []
    nfiles = 0
    for path in _py_files(roots):
        nfiles += 1
        problems.extend(lint_python(path))
    problems.extend(lint_cpp())
    for p in problems:
        print(p)
    print(f"lint: {nfiles} python files + cpp/, {len(problems)} problem(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
