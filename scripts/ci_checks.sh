#!/usr/bin/env bash
# Repo consistency checks, one entry point: metric-name lint, faultpoint/
# knob lint, and the perf-sentry self-check. Run from anywhere; wired
# into the tier-1 suite by tests/test_sentry.py so it cannot rot.
set -euo pipefail
cd "$(dirname "$0")/.."

python scripts/check_metric_names.py
python scripts/check_faultpoints.py
python -m dmlc_tpu.tools bench-gate --smoke
echo "ci_checks: all checks passed"
