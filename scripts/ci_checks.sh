#!/usr/bin/env bash
# Repo consistency checks, one entry point: metric-name lint, faultpoint/
# knob lint, and the perf-sentry self-check. Run from anywhere; wired
# into the tier-1 suite by tests/test_sentry.py so it cannot rot.
set -euo pipefail
cd "$(dirname "$0")/.."

python scripts/check_metric_names.py
python scripts/check_faultpoints.py
python -m dmlc_tpu.tools bench-gate --smoke

# obs-top --once smoke against a local StatusServer fixture: exercises
# the /metrics + /workers endpoint contract and the CLI's table path
# end to end (device telemetry metric names included).
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'EOF'
import json, sys, time

from dmlc_tpu.obs import plane
from dmlc_tpu.obs.metrics import Registry
from dmlc_tpu.tools import obs_top

reg = Registry()
reg.counter("dmlc_xla_compiles_total", "", fn="linear.step").inc(2)
reg.counter("dmlc_feed_h2d_bytes_total", "", feed="f0").inc(1 << 20)
reg.histogram("dmlc_feed_h2d_mbps", "", feed="f0").observe(512.0)
reg.gauge("dmlc_device_live_bytes", "", device="cpu:0").set(1 << 22)
reg.histogram("dmlc_feed_consume_ns", "", feed="f0").observe(2e6)

sp = plane.StatusPlane(num_workers=1)
blob, _ = plane.build_payload(rank=0, epoch=1, reg=reg)
sp.note_live(0, time.time(), "epoch=1")
sp.note_payload(0, json.loads(blob), time.time_ns())
srv = plane.StatusServer(sp, port=0)
srv.start()
try:
    rc = obs_top.main(["--once", "--status", "127.0.0.1:%d" % srv.port])
finally:
    srv.close()
if rc != 0:
    sys.exit("ci_checks: obs-top --once smoke failed (rc=%d)" % rc)
print("ci_checks: obs-top smoke OK")
EOF

# dispatcher-failover smoke: a 2-worker data fleet loses one worker to
# an injected crash mid-epoch; the lease table must still drain every
# chunk exactly once (requeue >= 1 proves the reassignment path ran).
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'EOF'
import sys, tempfile, os

from dmlc_tpu import resilience
from dmlc_tpu.data import BlockService, DataDispatcher, RemoteBlockParser

fd, path = tempfile.mkstemp(suffix=".svm")
with os.fdopen(fd, "w") as fh:
    for i in range(20):
        fh.write("%d 1:%d\n" % (i % 2, i))
try:
    resilience.reset()
    resilience.configure("service.worker_crash:nth=1")
    with DataDispatcher(path, nchunks=4, lease_s=1.0,
                        dead_after_s=0.75) as disp:
        workers = [BlockService(dispatcher=disp.address, nthread=1)
                   for _ in range(2)]
        try:
            p = RemoteBlockParser(disp.address, dispatcher=True)
            rows = sum(len(b) for b in p)
            p.close()
            ok = disp.join(timeout=30)
            snap = disp.snapshot()
        finally:
            for svc in workers:
                svc.close()
    if not ok or rows != 20:
        sys.exit("ci_checks: dispatcher smoke lost rows (%d/20, ok=%s)"
                 % (rows, ok))
    if snap["chunks"]["acked"] != snap["chunks"]["total"]:
        sys.exit("ci_checks: lease table not drained: %r" % (snap,))
    if snap["requeued"] < 1:
        sys.exit("ci_checks: the injected crash never forced a requeue")
finally:
    resilience.reset()
    os.unlink(path)
print("ci_checks: dispatcher failover smoke OK")
EOF

echo "ci_checks: all checks passed"
