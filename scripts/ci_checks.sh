#!/usr/bin/env bash
# Repo consistency checks, one entry point: metric-name lint, faultpoint/
# knob lint, and the perf-sentry self-check. Run from anywhere; wired
# into the tier-1 suite by tests/test_sentry.py so it cannot rot.
set -euo pipefail
cd "$(dirname "$0")/.."

python scripts/check_metric_names.py
python scripts/check_faultpoints.py
python -m dmlc_tpu.tools bench-gate --smoke

# obs-top --once smoke against a local StatusServer fixture: exercises
# the /metrics + /workers endpoint contract and the CLI's table path
# end to end (device telemetry metric names included).
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'EOF'
import json, sys, time

from dmlc_tpu.obs import plane
from dmlc_tpu.obs.metrics import Registry
from dmlc_tpu.tools import obs_top

reg = Registry()
reg.counter("dmlc_xla_compiles_total", "", fn="linear.step").inc(2)
reg.counter("dmlc_feed_h2d_bytes_total", "", feed="f0").inc(1 << 20)
reg.histogram("dmlc_feed_h2d_mbps", "", feed="f0").observe(512.0)
reg.gauge("dmlc_device_live_bytes", "", device="cpu:0").set(1 << 22)
reg.histogram("dmlc_feed_consume_ns", "", feed="f0").observe(2e6)

sp = plane.StatusPlane(num_workers=1)
blob, _ = plane.build_payload(rank=0, epoch=1, reg=reg)
sp.note_live(0, time.time(), "epoch=1")
sp.note_payload(0, json.loads(blob), time.time_ns())
srv = plane.StatusServer(sp, port=0)
srv.start()
try:
    rc = obs_top.main(["--once", "--status", "127.0.0.1:%d" % srv.port])
finally:
    srv.close()
if rc != 0:
    sys.exit("ci_checks: obs-top --once smoke failed (rc=%d)" % rc)
print("ci_checks: obs-top smoke OK")
EOF

# dispatcher-failover smoke: a 2-worker data fleet loses one worker to
# an injected crash mid-epoch; the lease table must still drain every
# chunk exactly once (requeue >= 1 proves the reassignment path ran).
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'EOF'
import sys, tempfile, os

from dmlc_tpu import resilience
from dmlc_tpu.data import BlockService, DataDispatcher, RemoteBlockParser

fd, path = tempfile.mkstemp(suffix=".svm")
with os.fdopen(fd, "w") as fh:
    for i in range(20):
        fh.write("%d 1:%d\n" % (i % 2, i))
try:
    resilience.reset()
    resilience.configure("service.worker_crash:nth=1")
    with DataDispatcher(path, nchunks=4, lease_s=1.0,
                        dead_after_s=0.75) as disp:
        workers = [BlockService(dispatcher=disp.address, nthread=1)
                   for _ in range(2)]
        try:
            p = RemoteBlockParser(disp.address, dispatcher=True)
            rows = sum(len(b) for b in p)
            p.close()
            ok = disp.join(timeout=30)
            snap = disp.snapshot()
        finally:
            for svc in workers:
                svc.close()
    if not ok or rows != 20:
        sys.exit("ci_checks: dispatcher smoke lost rows (%d/20, ok=%s)"
                 % (rows, ok))
    if snap["chunks"]["acked"] != snap["chunks"]["total"]:
        sys.exit("ci_checks: lease table not drained: %r" % (snap,))
    if snap["requeued"] < 1:
        sys.exit("ci_checks: the injected crash never forced a requeue")
finally:
    resilience.reset()
    os.unlink(path)
print("ci_checks: dispatcher failover smoke OK")
EOF

# two-job shared-cache smoke: tenants A and B read the SAME source over
# one fleet; job B must be served entirely from the shared source cache
# (zero chunk parses) with bit-identical rows — the PR 12 acceptance bar.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'EOF'
import sys, tempfile, os

from dmlc_tpu import resilience
from dmlc_tpu.data import (BlockService, DataDispatcher, RemoteBlockParser,
                           reset_source_cache, source_cache)

fd, path = tempfile.mkstemp(suffix=".svm")
with os.fdopen(fd, "w") as fh:
    for i in range(20):
        fh.write("%d 1:%d\n" % (i % 2, i))
try:
    resilience.reset()
    reset_source_cache()
    def drain(job):
        p = RemoteBlockParser(disp.address, dispatcher=True, job=job)
        sig = sorted((b.label.tobytes(), b.value.tobytes()) for b in p)
        p.close()
        return sig
    with DataDispatcher() as disp:
        disp.add_job("a", path, nchunks=4)
        disp.add_job("b", path, nchunks=4)
        with BlockService(dispatcher=disp.address, nthread=1) as svc:
            sig_a = drain("a")
            parsed_a = svc.chunks_parsed
            sig_b = drain("b")
            parsed_b = svc.chunks_parsed - parsed_a
            hits = source_cache().hits
        ok = disp.join(timeout=30, job="a") and disp.join(timeout=30,
                                                          job="b")
    if not ok:
        sys.exit("ci_checks: two-job smoke never drained both ledgers")
    if parsed_a != 4:
        sys.exit("ci_checks: job A parsed %d chunks, wanted 4" % parsed_a)
    if parsed_b != 0:
        sys.exit("ci_checks: job B re-parsed %d chunks; the shared cache "
                 "missed" % parsed_b)
    if hits < 4:
        sys.exit("ci_checks: cross-job hit count %d < 4" % hits)
    if sig_a != sig_b:
        sys.exit("ci_checks: tenants saw different bytes for one source")
finally:
    resilience.reset()
    reset_source_cache()
    os.unlink(path)
print("ci_checks: two-job shared-cache smoke OK (job B parsed 0 chunks)")
EOF

# parse-parity smoke: the scalar oracle, the numpy vector path, and (when
# loaded) the native core must produce byte-identical RowBlocks over a
# canned corpus of grammar corner cases. A digest mismatch here means the
# vectorized hot path and the reference parser have diverged.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'EOF'
import hashlib, sys

import numpy as np

from dmlc_tpu.data import vparse
from dmlc_tpu.data.row_block import RowBlockContainer

CORPUS = (
    b"1 1:1.5 3:2\n0 2:4\n",
    b"1:0.5 4:1e-3 7:2\n\n-1 12:3.25\n",          # blank line mid-chunk
    b"0.5:2.5 1:1 2:2\n1 qid:7 3:4\n",           # weighted head + qid
    b"1 1:1\r\n0 2:2\r\n",                        # CRLF
    b"1 5:1e308 6:5e-324 7:-0.0\n",              # huge/denormal/signed zero
    b"0 1048576:0.125 2097151:9\n",              # long feature ids
    b"1 1:1\n0 2:2",                              # no trailing newline
)

def digest(parse):
    h = hashlib.sha256()
    for chunk in CORPUS:
        out = RowBlockContainer()
        parse(chunk, out)
        blk = out.to_block()
        for arr in (blk.offset, blk.index, blk.label, blk.value,
                    blk.weight, blk.qid):
            h.update(b"|" if arr is None else np.ascontiguousarray(
                arr).tobytes())
    return h.hexdigest()

scalar = digest(vparse.parse_libsvm_scalar)
vector = digest(vparse.parse_libsvm_vector)
if scalar != vector:
    sys.exit("ci_checks: parse parity FAILED (scalar %s != vector %s)"
             % (scalar[:12], vector[:12]))

from dmlc_tpu import native
if native.available():
    from dmlc_tpu.data.parsers import _native_libsvm

    def native_parse(chunk, out):
        got = _native_libsvm(chunk)
        if got is None:
            sys.exit("ci_checks: native core refused a corpus chunk")
        blk = got.to_block()
        out.push_arrays(
            blk.label, np.diff(blk.offset), blk.index,
            value=blk.value, weight=blk.weight, qid=blk.qid)

    nat = digest(native_parse)
    if nat != scalar:
        sys.exit("ci_checks: parse parity FAILED (native %s != scalar %s)"
                 % (nat[:12], scalar[:12]))
    print("ci_checks: parse-parity smoke OK (scalar == vector == native)")
else:
    print("ci_checks: parse-parity smoke OK (scalar == vector; no native)")
EOF

echo "ci_checks: all checks passed"
