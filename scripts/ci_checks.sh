#!/usr/bin/env bash
# Repo consistency checks, one entry point: metric-name lint, faultpoint/
# knob lint, and the perf-sentry self-check. Run from anywhere; wired
# into the tier-1 suite by tests/test_sentry.py so it cannot rot.
set -euo pipefail
cd "$(dirname "$0")/.."

python scripts/check_metric_names.py
python scripts/check_faultpoints.py
python scripts/check_partition_rules.py
python -m dmlc_tpu.tools bench-gate --smoke

# obs-top --once smoke against a local StatusServer fixture: exercises
# the /metrics + /workers endpoint contract and the CLI's table path
# end to end (device telemetry metric names included).
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'EOF'
import json, sys, time

from dmlc_tpu.obs import plane
from dmlc_tpu.obs.metrics import Registry
from dmlc_tpu.tools import obs_top

reg = Registry()
reg.counter("dmlc_xla_compiles_total", "", fn="linear.step").inc(2)
reg.counter("dmlc_feed_h2d_bytes_total", "", feed="f0").inc(1 << 20)
reg.histogram("dmlc_feed_h2d_mbps", "", feed="f0").observe(512.0)
reg.gauge("dmlc_device_live_bytes", "", device="cpu:0").set(1 << 22)
reg.histogram("dmlc_feed_consume_ns", "", feed="f0").observe(2e6)

sp = plane.StatusPlane(num_workers=1)
blob, _ = plane.build_payload(rank=0, epoch=1, reg=reg)
sp.note_live(0, time.time(), "epoch=1")
sp.note_payload(0, json.loads(blob), time.time_ns())
srv = plane.StatusServer(sp, port=0)
srv.start()
try:
    rc = obs_top.main(["--once", "--status", "127.0.0.1:%d" % srv.port])
finally:
    srv.close()
if rc != 0:
    sys.exit("ci_checks: obs-top --once smoke failed (rc=%d)" % rc)
print("ci_checks: obs-top smoke OK")
EOF

# dispatcher-failover smoke: a 2-worker data fleet loses one worker to
# an injected crash mid-epoch; the lease table must still drain every
# chunk exactly once (requeue >= 1 proves the reassignment path ran).
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'EOF'
import sys, tempfile, os

from dmlc_tpu import resilience
from dmlc_tpu.data import BlockService, DataDispatcher, RemoteBlockParser

fd, path = tempfile.mkstemp(suffix=".svm")
with os.fdopen(fd, "w") as fh:
    for i in range(20):
        fh.write("%d 1:%d\n" % (i % 2, i))
try:
    resilience.reset()
    resilience.configure("service.worker_crash:nth=1")
    with DataDispatcher(path, nchunks=4, lease_s=1.0,
                        dead_after_s=0.75) as disp:
        workers = [BlockService(dispatcher=disp.address, nthread=1)
                   for _ in range(2)]
        try:
            p = RemoteBlockParser(disp.address, dispatcher=True)
            rows = sum(len(b) for b in p)
            p.close()
            ok = disp.join(timeout=30)
            snap = disp.snapshot()
        finally:
            for svc in workers:
                svc.close()
    if not ok or rows != 20:
        sys.exit("ci_checks: dispatcher smoke lost rows (%d/20, ok=%s)"
                 % (rows, ok))
    if snap["chunks"]["acked"] != snap["chunks"]["total"]:
        sys.exit("ci_checks: lease table not drained: %r" % (snap,))
    if snap["requeued"] < 1:
        sys.exit("ci_checks: the injected crash never forced a requeue")
finally:
    resilience.reset()
    os.unlink(path)
print("ci_checks: dispatcher failover smoke OK")
EOF

# two-job shared-cache smoke: tenants A and B read the SAME source over
# one fleet; job B must be served entirely from the shared source cache
# (zero chunk parses) with bit-identical rows — the PR 12 acceptance bar.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'EOF'
import sys, tempfile, os

from dmlc_tpu import resilience
from dmlc_tpu.data import (BlockService, DataDispatcher, RemoteBlockParser,
                           reset_source_cache, source_cache)

fd, path = tempfile.mkstemp(suffix=".svm")
with os.fdopen(fd, "w") as fh:
    for i in range(20):
        fh.write("%d 1:%d\n" % (i % 2, i))
try:
    resilience.reset()
    reset_source_cache()
    def drain(job):
        p = RemoteBlockParser(disp.address, dispatcher=True, job=job)
        sig = sorted((b.label.tobytes(), b.value.tobytes()) for b in p)
        p.close()
        return sig
    with DataDispatcher() as disp:
        disp.add_job("a", path, nchunks=4)
        disp.add_job("b", path, nchunks=4)
        with BlockService(dispatcher=disp.address, nthread=1) as svc:
            sig_a = drain("a")
            parsed_a = svc.chunks_parsed
            sig_b = drain("b")
            parsed_b = svc.chunks_parsed - parsed_a
            hits = source_cache().hits
        ok = disp.join(timeout=30, job="a") and disp.join(timeout=30,
                                                          job="b")
    if not ok:
        sys.exit("ci_checks: two-job smoke never drained both ledgers")
    if parsed_a != 4:
        sys.exit("ci_checks: job A parsed %d chunks, wanted 4" % parsed_a)
    if parsed_b != 0:
        sys.exit("ci_checks: job B re-parsed %d chunks; the shared cache "
                 "missed" % parsed_b)
    if hits < 4:
        sys.exit("ci_checks: cross-job hit count %d < 4" % hits)
    if sig_a != sig_b:
        sys.exit("ci_checks: tenants saw different bytes for one source")
finally:
    resilience.reset()
    reset_source_cache()
    os.unlink(path)
print("ci_checks: two-job shared-cache smoke OK (job B parsed 0 chunks)")
EOF

# parse-parity smoke: the scalar oracle, the numpy vector path, and (when
# loaded) the native core must produce byte-identical RowBlocks over a
# canned corpus of grammar corner cases. A digest mismatch here means the
# vectorized hot path and the reference parser have diverged.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'EOF'
import hashlib, sys

import numpy as np

from dmlc_tpu.data import vparse
from dmlc_tpu.data.row_block import RowBlockContainer

CORPUS = (
    b"1 1:1.5 3:2\n0 2:4\n",
    b"1:0.5 4:1e-3 7:2\n\n-1 12:3.25\n",          # blank line mid-chunk
    b"0.5:2.5 1:1 2:2\n1 qid:7 3:4\n",           # weighted head + qid
    b"1 1:1\r\n0 2:2\r\n",                        # CRLF
    b"1 5:1e308 6:5e-324 7:-0.0\n",              # huge/denormal/signed zero
    b"0 1048576:0.125 2097151:9\n",              # long feature ids
    b"1 1:1\n0 2:2",                              # no trailing newline
)

def digest(parse):
    h = hashlib.sha256()
    for chunk in CORPUS:
        out = RowBlockContainer()
        parse(chunk, out)
        blk = out.to_block()
        for arr in (blk.offset, blk.index, blk.label, blk.value,
                    blk.weight, blk.qid):
            h.update(b"|" if arr is None else np.ascontiguousarray(
                arr).tobytes())
    return h.hexdigest()

scalar = digest(vparse.parse_libsvm_scalar)
vector = digest(vparse.parse_libsvm_vector)
if scalar != vector:
    sys.exit("ci_checks: parse parity FAILED (scalar %s != vector %s)"
             % (scalar[:12], vector[:12]))

from dmlc_tpu import native
if native.available():
    from dmlc_tpu.data.parsers import _native_libsvm

    def native_parse(chunk, out):
        got = _native_libsvm(chunk)
        if got is None:
            sys.exit("ci_checks: native core refused a corpus chunk")
        blk = got.to_block()
        out.push_arrays(
            blk.label, np.diff(blk.offset), blk.index,
            value=blk.value, weight=blk.weight, qid=blk.qid)

    nat = digest(native_parse)
    if nat != scalar:
        sys.exit("ci_checks: parse parity FAILED (native %s != scalar %s)"
                 % (nat[:12], scalar[:12]))
    print("ci_checks: parse-parity smoke OK (scalar == vector == native)")
else:
    print("ci_checks: parse-parity smoke OK (scalar == vector; no native)")
EOF

# device-resident fast-path smoke: the same short LibSVM fit run two
# ways — the legacy python staging path and DMLC_TPU_DEVICE_RESIDENT=1
# (pad-in-place pool emit + donated batched put) — both pinned to the
# vector parse backend so only the staging path differs. Loss history
# and final params must be BIT-identical, and neither arm may recompile
# past its warmup epoch.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" DMLC_TPU_PARSE_BACKEND=vector \
python - <<'EOF'
import os, sys, tempfile

import numpy as np

from dmlc_tpu.models import LinearLearner
from dmlc_tpu.obs import device_telemetry as dt

NF, ROWS = 12, 400
rng = np.random.RandomState(3)
fd, svm = tempfile.mkstemp(suffix=".svm")
with os.fdopen(fd, "w") as fh:
    for i in range(ROWS):
        ids = np.sort(rng.choice(NF, size=1 + i % 4, replace=False))
        fh.write("%d %s\n" % (i % 2, " ".join(
            "%d:%.4f" % (j, rng.rand()) for j in ids)))

def fit(resident):
    os.environ.pop("DMLC_TPU_DEVICE_RESIDENT", None)
    if resident:
        os.environ["DMLC_TPU_DEVICE_RESIDENT"] = "1"
    dt.reset()
    learner = LinearLearner(objective="logistic", learning_rate=0.1,
                            num_features=NF)
    hist = list(learner.fit_uri(svm, batch_size=64, epochs=1,
                                num_features=NF))
    warm = dict(dt.compile_counts())
    hist += list(learner.fit_uri(svm, batch_size=64, epochs=2,
                                 num_features=NF))
    if dict(dt.compile_counts()) != warm:
        sys.exit("ci_checks: resident smoke recompiled past warmup "
                 "(resident=%s): %r -> %r"
                 % (resident, warm, dt.compile_counts()))
    return {"hist": [float(h).hex() for h in hist],
            "w": np.asarray(learner.params["w"]).tobytes().hex(),
            "b": np.asarray(learner.params["b"]).tobytes().hex()}

try:
    legacy = fit(False)
    resident = fit(True)
finally:
    os.environ.pop("DMLC_TPU_DEVICE_RESIDENT", None)
    os.unlink(svm)
if legacy != resident:
    sys.exit("ci_checks: resident fast path diverged from legacy:\n"
             "  legacy   %r\n  resident %r" % (legacy, resident))
print("ci_checks: device-resident smoke OK "
      "(bit-identical fit, zero post-warmup recompiles)")
EOF

# Pallas sparse-step parity: the COO segment-sum kernel (interpret mode
# off-TPU) vs XLA's scatter spmv on exactly-representable f32 data —
# sums are integers, so ANY reduction order must produce identical bits.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'EOF'
import sys

import numpy as np

from dmlc_tpu.ops import pallas_kernels

if not pallas_kernels.available:
    print("ci_checks: pallas spmv parity SKIPPED (pallas unavailable)")
    sys.exit(0)

import jax.numpy as jnp

from dmlc_tpu.ops.spmv import spmv, spmv_pallas

rng = np.random.RandomState(11)
entries, rows, nfeat = 1024, 192, 64
values = rng.randint(1, 5, entries).astype(np.float32)
indices = rng.randint(0, nfeat, entries).astype(np.int32)
rid = np.sort(rng.randint(0, rows, entries)).astype(np.int32)
vec = rng.randint(-4, 5, nfeat).astype(np.float32)
ref = spmv(jnp.asarray(values), jnp.asarray(indices), jnp.asarray(rid),
           jnp.asarray(vec), rows)
got = spmv_pallas(jnp.asarray(values), jnp.asarray(indices),
                  jnp.asarray(rid), jnp.asarray(vec), rows,
                  interpret=True)
if not np.array_equal(np.asarray(ref), np.asarray(got)):
    sys.exit("ci_checks: pallas spmv parity FAILED (max delta %g)"
             % float(np.abs(np.asarray(ref) - np.asarray(got)).max()))
print("ci_checks: pallas spmv parity OK (bit-identical vs XLA scatter)")
EOF

# SPMD collective smoke: the same short LibSVM fit run two ways — a
# single-process 2-virtual-device mesh with DMLC_TPU_COLLECTIVE=device
# (gradient allreduce as the in-graph bucketed psum) and a 2-process
# socket-engine world on the hostsync fallback (fused-buffer
# collective.allreduce). Loss history and final params must be
# BIT-identical, and the SPMD run must move zero collective D2H bytes.
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=2" \
DMLC_TPU_COLLECTIVE=device python - <<'EOF'
import json, os, shutil, subprocess, sys, tempfile

import numpy as np

NF, ROWS, EPOCHS = 8, 64, 3
HYPER = dict(objective="logistic", learning_rate=0.1, num_features=NF)

# the full file for the mesh run plus a pre-split half per socket
# worker: rank r must read EXACTLY the rows the mesh places on device r
# (InputSplit's newline-seek hands a boundary row to part 0, which
# would skew step counts and partial-sum row sets)
workdir = tempfile.mkdtemp()
data = os.path.join(workdir, "toy.svm")
halves = [os.path.join(workdir, "toy.%d.svm" % r) for r in range(2)]
rows = []
for i in range(ROWS):
    feats = " ".join(
        "%d:%d" % (j + 1, (i * 7 + j * 3) % 10) for j in range(NF))
    rows.append("%d %s\n" % (i % 2, feats))
open(data, "w").write("".join(rows))
open(halves[0], "w").write("".join(rows[: ROWS // 2]))
open(halves[1], "w").write("".join(rows[ROWS // 2:]))

WORKER = r'''
import json, os, sys
rank, port, data, out = (int(sys.argv[1]), int(sys.argv[2]),
                         sys.argv[3], sys.argv[4])
from dmlc_tpu import collective
from dmlc_tpu.models import LinearLearner
collective.init()  # DMLC_TPU_COLLECTIVE=socket forces the tree engine
assert collective.engine_kind() == "socket", collective.engine_kind()
learner = LinearLearner(sync="host", objective="logistic",
                        learning_rate=0.1, num_features=8)
hist = learner.fit_uri(data, batch_size=32, epochs=3, num_features=8,
                       part_index=0, num_parts=1)
import numpy as np
json.dump({"hist": [h.hex() for h in map(float, hist)],
           "w": np.asarray(learner.params["w"]).tobytes().hex(),
           "b": np.asarray(learner.params["b"]).tobytes().hex()},
          open(out, "w"))
collective.finalize()
'''

worker_py = os.path.join(workdir, "worker.py")
open(worker_py, "w").write(WORKER)

from dmlc_tpu.tracker.rendezvous import RabitTracker
tracker = RabitTracker("127.0.0.1", 2, port=19590, port_end=19690)
tracker.start(2)
procs, outs = [], []
for rank in range(2):
    out = os.path.join(workdir, "r%d.json" % rank)
    outs.append(out)
    env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS="",
               DMLC_TPU_COLLECTIVE="socket",
               DMLC_TRACKER_URI="127.0.0.1",
               DMLC_TRACKER_PORT=str(tracker.port),
               DMLC_TASK_ID=str(rank), PYTHONPATH=os.getcwd())
    procs.append(subprocess.Popen(
        [sys.executable, worker_py, str(rank), str(tracker.port),
         halves[rank], out], env=env))
for p in procs:
    if p.wait(timeout=240) != 0:
        sys.exit("ci_checks: socket hostsync worker failed (rc=%d)"
                 % p.returncode)
tracker.join(); tracker.close()
socket_runs = [json.load(open(o)) for o in outs]
if socket_runs[0] != socket_runs[1]:
    sys.exit("ci_checks: socket ranks disagree on the fitted model")

# the mesh twin: whole file (world=1), global batch 64 sharded 32/32
import jax
from jax.sharding import Mesh
from dmlc_tpu import collective, obs
from dmlc_tpu.models import LinearLearner
collective.init()  # DMLC_TPU_COLLECTIVE=device forces DeviceEngine
assert collective.engine_kind() == "device", collective.engine_kind()
mesh = Mesh(np.asarray(jax.devices()), ("dp",))
learner = LinearLearner(mesh=mesh, **HYPER)
hist = learner.fit_uri(data, batch_size=ROWS, epochs=EPOCHS,
                       num_features=NF)
spmd = {"hist": [h.hex() for h in map(float, hist)],
        "w": np.asarray(learner.params["w"]).tobytes().hex(),
        "b": np.asarray(learner.params["b"]).tobytes().hex()}
if spmd != socket_runs[0]:
    sys.exit("ci_checks: SPMD psum run diverged from the socket tree:\n"
             "  spmd   %r\n  socket %r" % (spmd, socket_runs[0]))
# the acceptance claim in observable form: training's gradient sync
# crossed ICI in-graph, so the host-path collective moved nothing back
d2h = obs.registry().counter(
    "dmlc_collective_d2h_bytes_total", "", op="allreduce").value
if d2h != 0:
    sys.exit("ci_checks: SPMD run copied %d collective D2H bytes" % d2h)
shutil.rmtree(workdir, ignore_errors=True)
print("ci_checks: SPMD collective smoke OK "
      "(device psum == socket tree, bit-exact; 0 collective D2H bytes)")
EOF

# watchdog/goodput smoke: a short linear fit with a scripted mid-run
# slowdown (the feed throttled from epoch 4 on) must trip the collapse
# watchdog through the fit loop's own ledger — exactly one
# watchdog.alert in the flight-recorder dump plus the
# dmlc_watchdog_alerts_total{kind="collapse"} bump — and the status
# plane must serve the run's roofline attribution at /goodput.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'EOF'
import json, os, shutil, sys, tempfile, time, urllib.request

import numpy as np

from dmlc_tpu import obs
from dmlc_tpu.data.parsers import LibSVMParser
from dmlc_tpu.device.feed import BatchSpec, DeviceFeed
from dmlc_tpu.io.input_split import create_input_split
from dmlc_tpu.models.linear import LinearLearner
from dmlc_tpu.obs import flight, plane

workdir = tempfile.mkdtemp(prefix="dmlc_wd_smoke_")
rec = flight.configure(workdir, capacity=64, rank=0, install=False)

NF, ROWS, EPOCHS, SLOW_FROM = 16, 600, 6, 4
rng = np.random.RandomState(0)
lines = []
for i in range(ROWS):
    ids = np.sort(rng.choice(NF, size=1 + i % 5, replace=False))
    lines.append("%d %s" % (i % 2, " ".join(
        "%d:%.4f" % (j, rng.rand()) for j in ids)))
svm = os.path.join(workdir, "t.svm")
with open(svm, "w") as fh:
    fh.write("\n".join(lines) + "\n")


class ThrottledFeed:
    """The scripted regression: from epoch SLOW_FROM on every batch
    costs an extra 250 ms, collapsing rows/s ~100x mid-run."""

    def __init__(self, feed):
        self._feed = feed
        self._epoch = -1

    def __getattr__(self, name):
        return getattr(self._feed, name)

    def __iter__(self):
        self._epoch += 1
        for batch in self._feed:
            if self._epoch >= SLOW_FROM:
                time.sleep(0.25)
            yield batch


reg = obs.registry()
t0_ns = time.time_ns()
m0 = reg.flat_values()

split = create_input_split(svm, 0, 1, "text", threaded=False)
feed = DeviceFeed(
    LibSVMParser(split, nthread=1),
    BatchSpec(batch_size=128, layout="dense", num_features=NF))
learner = LinearLearner(learning_rate=0.1)
learner.fit_feed(ThrottledFeed(feed), epochs=EPOCHS)
feed.close()
t1_ns = time.time_ns()
m1 = reg.flat_values()

# the collapse must have fired exactly once (fire-once hysteresis:
# epoch 4 trips it, epoch 5 stays silent) and landed in the dump
alerts = [r for r in rec.records() if r["kind"] == "watchdog.alert"]
if [a.get("alert") for a in alerts] != ["collapse"]:
    sys.exit("ci_checks: expected one collapse alert, got %r" % alerts)
bumped = reg.counter(
    "dmlc_watchdog_alerts_total", "", kind="collapse").value
if bumped != 1:
    sys.exit("ci_checks: alerts counter = %r, want 1" % bumped)
dump_path = rec.dump("watchdog_smoke")
dumped = json.load(open(dump_path))["records"]
if not any(r["kind"] == "watchdog.alert" and r.get("alert") == "collapse"
           for r in dumped):
    sys.exit("ci_checks: collapse alert missing from flight dump")

# the plane rolls the same run's heartbeat delta into /goodput
sp = plane.StatusPlane(num_workers=1, heartbeat_gap=60.0)
sp.note_payload(0, {"sent_unix_ns": t0_ns, "anchor_unix_ns": 1,
                    "metrics": m0, "spans": []}, recv_unix_ns=t0_ns)
sp.note_payload(0, {"sent_unix_ns": t1_ns, "anchor_unix_ns": 1,
                    "metrics": m1, "spans": []}, recv_unix_ns=t1_ns)
srv = plane.StatusServer(sp, port=0)
srv.start()
try:
    url = "http://127.0.0.1:%d/goodput" % srv.port
    with urllib.request.urlopen(url, timeout=10) as resp:
        body = json.loads(resp.read())
finally:
    srv.close()
att = body["ranks"]["0"]
if att["binding"] != "device_step":
    sys.exit("ci_checks: /goodput binding = %r, want device_step "
             "(the throttle rides the consume span)" % att["binding"])
if att["counters"]["rows"] != ROWS * EPOCHS:
    sys.exit("ci_checks: /goodput rows = %r" % att["counters"]["rows"])
if not body["job"] or body["job"]["binding"] != "device_step":
    sys.exit("ci_checks: job roll-up missing or wrong: %r" % body["job"])
flight.reset()
shutil.rmtree(workdir, ignore_errors=True)
print("ci_checks: watchdog smoke OK "
      "(collapse fired once, dumped; /goodput names device_step)")
EOF

# determinism-audit smoke: the same short fit run as a 2-process pair
# with DMLC_TPU_AUDIT=1. Clean pair: zero divergences, no replay
# bundles, bit-identical model digest chains across ranks. Faulted
# pair: rank 1 gets a single silently-corrupted chunk (the
# audit.corrupt faultpoint flips one digit — parseable, wrong bytes);
# the worker's epoch self-check must localize the fork to the exact
# (parse, rank 1, seq 0) in audit-rank1.json, and a tracker-side
# AuditPlane fed both ranks' exports must flag the cross-rank model
# fork. Finally the disabled-vs-enabled parse overhead is measured
# (min-of-3; <2% steady-state target, generous CI bound).
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'EOF'
import glob, json, os, shutil, subprocess, sys, tempfile, time

import numpy as np

workdir = tempfile.mkdtemp(prefix="dmlc_audit_smoke_")
NF, ROWS = 12, 400
rng = np.random.RandomState(5)
svm = os.path.join(workdir, "a.svm")
with open(svm, "w") as fh:
    for i in range(ROWS):
        ids = np.sort(rng.choice(NF, size=1 + i % 4, replace=False))
        fh.write("%d %s\n" % (i % 2, " ".join(
            "%d:%.4f" % (j, rng.rand()) for j in ids)))

WORKER = r'''
import json, sys
data, out = sys.argv[1], sys.argv[2]
import numpy as np
from dmlc_tpu.models import LinearLearner
from dmlc_tpu.obs import audit
learner = LinearLearner(objective="logistic", learning_rate=0.1,
                        num_features=12)
list(learner.fit_uri(data, batch_size=64, epochs=2, num_features=12))
a = audit.auditor()
json.dump({"divergences": a.snapshot()["divergences"],
           "export": a.export(),
           "w": np.asarray(learner.params["w"]).tobytes().hex()},
          open(out, "w"))
'''
worker_py = os.path.join(workdir, "worker.py")
open(worker_py, "w").write(WORKER)

def run_pair(tag, faults=None):
    rundir = os.path.join(workdir, tag)
    os.makedirs(rundir)
    procs, outs = [], []
    for rank in range(2):
        out = os.path.join(rundir, "r%d.json" % rank)
        outs.append(out)
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   DMLC_TPU_AUDIT="1", DMLC_TPU_NTHREAD="1",
                   DMLC_TASK_ID=str(rank), PYTHONPATH=os.getcwd())
        env.pop("DMLC_TPU_FAULTS", None)
        env.pop("DMLC_TPU_STATUS_PORT", None)
        if rank == 1 and faults:
            env["DMLC_TPU_FAULTS"] = faults
        procs.append(subprocess.Popen(
            [sys.executable, worker_py, svm, out], env=env, cwd=rundir))
    for p in procs:
        if p.wait(timeout=240) != 0:
            sys.exit("ci_checks: audit smoke worker failed (rc=%d)"
                     % p.returncode)
    return rundir, [json.load(open(o)) for o in outs]

def plane_forks(reports, rundir):
    from dmlc_tpu.obs.audit import AuditPlane
    from dmlc_tpu.obs.metrics import Registry
    out_dir = os.path.join(rundir, "tracker")
    os.makedirs(out_dir, exist_ok=True)
    plane = AuditPlane(reg=Registry(), out_dir=out_dir)
    found = []
    for rank, rep in enumerate(reports):
        found += plane.note_audit(rank, rep["export"])
    return found

# clean pair: identical inputs -> identical chains, zero divergences
rundir, reports = run_pair("clean")
if any(rep["divergences"] for rep in reports):
    sys.exit("ci_checks: clean audit run reported divergences: %r"
             % [rep["divergences"] for rep in reports])
if glob.glob(os.path.join(rundir, "audit-rank*.json")):
    sys.exit("ci_checks: clean audit run wrote a replay bundle")
heads = [rep["export"]["chains"]["model"]["head"] for rep in reports]
if heads[0] != heads[1] or reports[0]["w"] != reports[1]["w"]:
    sys.exit("ci_checks: clean ranks disagree on the model chain")
if plane_forks(reports, rundir):
    sys.exit("ci_checks: AuditPlane flagged a fork on the clean pair")

# faulted pair: one corrupted chunk on rank 1, epoch 0
rundir, reports = run_pair("corrupt", faults="audit.corrupt:nth=1")
if reports[0]["divergences"]:
    sys.exit("ci_checks: corruption on rank 1 flagged rank 0: %r"
             % reports[0]["divergences"])
divs = reports[1]["divergences"]
if not divs or (divs[0]["stage"], divs[0]["seq"]) != ("parse", 0):
    sys.exit("ci_checks: rank 1 self-check missed the fork "
             "(want stage=parse seq=0): %r" % divs)
bundle_file = os.path.join(rundir, "audit-rank1.json")
if os.path.exists(os.path.join(rundir, "audit-rank0.json")):
    sys.exit("ci_checks: clean rank 0 wrote a replay bundle")
bundle = json.load(open(bundle_file))
if (bundle["divergence"]["stage"], bundle["divergence"]["seq"],
        bundle["rank"]) != ("parse", 0, 1):
    sys.exit("ci_checks: bundle localization wrong: %r"
             % bundle["divergence"])
forks = plane_forks(reports, rundir)
if not forks or (forks[0]["stage"], forks[0]["rank"]) != ("model", 1):
    sys.exit("ci_checks: AuditPlane missed the cross-rank model fork: %r"
             % forks)
rc = subprocess.call([sys.executable, "-m", "dmlc_tpu.tools",
                      "audit-report", rundir],
                     stdout=subprocess.DEVNULL)
if rc != 1:
    sys.exit("ci_checks: audit-report rc=%d on a diverged bundle, "
             "want 1" % rc)

# overhead: disabled vs full-audit parse pass over a bigger corpus
from dmlc_tpu.data.parsers import LibSVMParser
from dmlc_tpu.io.input_split import create_input_split
from dmlc_tpu.obs import audit as audit_mod

big = os.path.join(workdir, "big.svm")
with open(big, "w") as fh:
    for i in range(20000):
        fh.write("%d %d:%.4f %d:%.4f\n"
                 % (i % 2, i % NF, rng.rand(), NF + i % NF, rng.rand()))

def parse_pass():
    split = create_input_split(big, 0, 1, "text", threaded=False)
    parser = LibSVMParser(split, nthread=1)
    n = sum(1 for _ in parser)
    parser.close()
    return n

def best_of(trials=3):
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        parse_pass()
        best = min(best, time.perf_counter() - t0)
    return best

os.environ.pop("DMLC_TPU_FAULTS", None)
os.environ.pop("DMLC_TPU_AUDIT", None)
audit_mod.reset_auditor()
parse_pass()  # warm the page cache + import path before timing
base = best_of()
os.environ["DMLC_TPU_AUDIT"] = "1"
audit_mod.reset_auditor()
parse_pass()
audited = best_of()
if audit_mod.auditor().snapshot()["divergences"]:
    sys.exit("ci_checks: overhead pass reported divergences")
os.environ.pop("DMLC_TPU_AUDIT", None)
audit_mod.reset_auditor()
ratio = audited / base if base > 0 else 1.0
print("ci_checks: audit parse overhead x%.3f (steady-state target "
      "<1.02)" % ratio)
if ratio > 1.15:
    sys.exit("ci_checks: audit overhead x%.3f exceeds the CI bound "
             "1.15" % ratio)
shutil.rmtree(workdir, ignore_errors=True)
print("ci_checks: audit smoke OK (self-check + cross-rank localized "
      "(parse, rank 1, seq 0); clean pair chain-identical)")
EOF

# baked-shard smoke: bake a toy corpus through the CLI, prove the
# ShardParser replays the text parser's rows bit-identically
# (rows_digest over the canonical audit stream), then run a shuffled
# (DMLC_TPU_SHUFFLE=13) 2-worker dispatcher epoch with the determinism
# audit armed — the global permutation must preserve the per-epoch
# row-set exactly (order-insensitive digest == unshuffled aggregate)
# with ZERO audit divergences on the pre-tokenized fast path.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'EOF'
import hashlib, os, sys, tempfile

import numpy as np

from dmlc_tpu import resilience
from dmlc_tpu.data import (BlockService, DataDispatcher, RemoteBlockParser,
                           create_parser, reset_source_cache)
from dmlc_tpu.obs import audit
from dmlc_tpu.obs.audit import rows_digest
from dmlc_tpu.tools import bake

ROWS = 120
workdir = tempfile.mkdtemp(prefix="dmlc_shard_smoke_")
svm = os.path.join(workdir, "toy.svm")
dst = os.path.join(workdir, "toy.dtsh")
rng = np.random.RandomState(9)
with open(svm, "w") as fh:
    for i in range(ROWS):
        ids = np.sort(rng.choice(16, size=1 + i % 5, replace=False))
        fh.write("%d %s\n" % (i, " ".join(
            "%d:%.4f" % (j, rng.rand()) for j in ids)))


def drain_digest(parser):
    from dmlc_tpu.data.row_block import RowBlockContainer
    out = RowBlockContainer()
    for block in parser:
        out.push_block(block)
    parser.close()
    return rows_digest(out)


def rowset_digest(faults=None, shuffle=None):
    """Order-insensitive exact digest of one dispatcher epoch's rows:
    per-row (label, indices, values) signatures, sorted then hashed."""
    resilience.reset()
    reset_source_cache()
    audit.reset_auditor()
    os.environ.pop("DMLC_TPU_SHUFFLE", None)
    if shuffle is not None:
        os.environ["DMLC_TPU_SHUFFLE"] = str(shuffle)
    if faults:
        resilience.configure(faults)
    sigs = []
    with DataDispatcher(dst, nchunks=4, lease_s=1.0,
                        dead_after_s=0.75) as disp:
        workers = [BlockService(dispatcher=disp.address, nthread=1)
                   for _ in range(2)]
        try:
            p = RemoteBlockParser(disp.address, dispatcher=True)
            for b in p:
                for r in range(len(b)):
                    lo, hi = b.offset[r], b.offset[r + 1]
                    sigs.append(b.label[r].tobytes()
                                + b.index[lo:hi].tobytes()
                                + b.value[lo:hi].tobytes())
            p.close()
            ok = disp.join(timeout=30)
        finally:
            for svc in workers:
                svc.close()
    if not ok or len(sigs) != ROWS:
        sys.exit("ci_checks: shard smoke lost rows (%d/%d, ok=%s)"
                 % (len(sigs), ROWS, ok))
    h = hashlib.sha256()
    for sig in sorted(sigs):
        h.update(sig)
    return h.hexdigest()


try:
    if bake.main([svm, dst, "--format", "libsvm",
                  "--rows-per-window", "32"]) != 0:
        sys.exit("ci_checks: bake CLI failed")
    text = drain_digest(create_parser(svm, 0, 1, data_format="libsvm"))
    baked = drain_digest(create_parser(dst, 0, 1))
    if baked != text:
        sys.exit("ci_checks: baked shard is NOT bit-identical to the "
                 "text parse (%s != %s)" % (baked[:12], text[:12]))
    os.environ["DMLC_TPU_AUDIT"] = "1"
    plain = rowset_digest()
    shuffled = rowset_digest(shuffle=13)
    if shuffled != plain:
        sys.exit("ci_checks: shuffled epoch changed the row-set")
    divs = audit.auditor().snapshot()["divergences"]
    if divs:
        sys.exit("ci_checks: shard smoke audit divergences: %r" % divs)
finally:
    os.environ.pop("DMLC_TPU_AUDIT", None)
    os.environ.pop("DMLC_TPU_SHUFFLE", None)
    resilience.reset()
    reset_source_cache()
    audit.reset_auditor()
    import shutil
    shutil.rmtree(workdir, ignore_errors=True)
print("ci_checks: baked-shard smoke OK (bake == text bit-exact; "
      "shuffled 2-worker epoch row-set identical, 0 divergences)")
EOF

# preemption smoke: a 2-process dmlc-submit fit with job snapshots and
# the determinism audit armed is SIGTERMed mid-epoch on both ranks once
# each wrote its epoch-0 snapshot part; each rank finalizes a just-in-time
# coordinated snapshot, exits with the relaunch code (75), the launcher
# relaunches without consuming attempts, and the resumed job's per-rank
# final params + loss history + audit chain heads are bit-identical to
# an uninterrupted run with zero audit divergences.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'EOF'
import os, shutil, subprocess, sys, tempfile

import numpy as np

WORKER = r'''
import hashlib, os, signal, sys, threading, time
import numpy as np
from dmlc_tpu import collective as rabit
from dmlc_tpu.models import LinearLearner
from dmlc_tpu.obs.audit import auditor

DATA, SNAP, KILL, SENTDIR = sys.argv[1:5]
NFEAT, EPOCHS = 6, 4

rabit.init()
rank = rabit.rank()
sentinel = os.path.join(SENTDIR, "life.rank%d" % rank)
first = not os.path.exists(sentinel)
if first:
    with open(sentinel, "w") as fh:
        fh.write("armed")
if KILL == "sigterm" and first:
    # the "cloud" preempts this host: once this rank wrote its epoch-0
    # snapshot part it gets a real SIGTERM, solidly mid-epoch-1 for the
    # rank. Keying on the rank's OWN part (not the global LATEST, which
    # needs every drifting rank's part + the rank-0 barrier) keeps the
    # kill deterministically inside the fit.
    def preempt_host():
        part = os.path.join(SNAP, "snap_v1.rank%d" % rank)
        while not os.path.exists(part):
            time.sleep(0.002)
        os.kill(os.getpid(), signal.SIGTERM)
    threading.Thread(target=preempt_host, daemon=True).start()

model = LinearLearner(learning_rate=0.5)
history = model.fit_uri(DATA, batch_size=16, epochs=EPOCHS,
                        num_features=NFEAT, drop_remainder=True,
                        snapshot_uri=SNAP, resume=not first)
blob = b"".join(np.ascontiguousarray(np.asarray(model.params[k]))
                .tobytes() for k in ("w", "b"))
blob += repr([round(float(x), 12) for x in history]).encode()
audit = auditor()
head = (audit.export_state() or {}).get("model", {}).get("head", "-")
div = len(getattr(audit, "divergences", ()))
rabit.tracker_print(
    "RESULT rank=%d digest=%s epochs=%d head=%s div=%d"
    % (rank, hashlib.sha256(blob).hexdigest()[:16], len(history),
       (head or "-")[:16], div))
rabit.finalize()
'''

workdir = tempfile.mkdtemp(prefix="dmlc_preempt_smoke_")
rng = np.random.RandomState(23)
data = os.path.join(workdir, "p.svm")
with open(data, "w") as fh:
    for _ in range(320):
        x = rng.rand(6)
        fh.write("%d %s\n" % (int(x.sum() > 3), " ".join(
            "%d:%.6f" % (j, x[j]) for j in range(6))))
worker_py = os.path.join(workdir, "worker.py")
open(worker_py, "w").write(WORKER)


def run_job(tag, kill, max_attempts):
    snap = os.path.join(workdir, "snap_%s" % tag)
    sent = os.path.join(workdir, "sent_%s" % tag)
    os.makedirs(sent)
    env = dict(os.environ, JAX_PLATFORMS="cpu", DMLC_TPU_AUDIT="1",
               DMLC_TPU_PREEMPT_DEADLINE_S="10",
               PYTHONPATH=os.getcwd())
    env.pop("DMLC_TPU_FAULTS", None)
    proc = subprocess.run(
        [sys.executable, "dmlc-submit", "--cluster", "local", "-n", "2",
         "--max-attempts", str(max_attempts), "--host-ip", "127.0.0.1",
         sys.executable, worker_py, data, snap, kill, sent],
        capture_output=True, text=True, timeout=300, env=env)
    out = proc.stdout + proc.stderr
    if proc.returncode != 0:
        sys.exit("ci_checks: preemption smoke %s run failed (rc=%d)\n%s"
                 % (tag, proc.returncode, out))
    results = {}
    for line in out.splitlines():
        if "RESULT" in line:
            kv = dict(p.split("=")
                      for p in line.split("RESULT", 1)[1].split())
            results[int(kv["rank"])] = kv
    if sorted(results) != [0, 1]:
        sys.exit("ci_checks: preemption smoke %s: missing RESULT "
                 "lines\n%s" % (tag, out))
    for r, kv in sorted(results.items()):
        if int(kv["epochs"]) != 4:
            sys.exit("ci_checks: %s rank %d finished %s epochs, want 4"
                     % (tag, r, kv["epochs"]))
        if int(kv["div"]) != 0:
            sys.exit("ci_checks: %s rank %d reported %s audit "
                     "divergences" % (tag, r, kv["div"]))
    return results, out


try:
    clean, _ = run_job("clean", "none", max_attempts=1)
    chaos, out = run_job("sigterm", "sigterm", max_attempts=2)
    if "preempted (exit 75)" not in out:
        sys.exit("ci_checks: SIGTERM never engaged the exit-75 relaunch "
                 "path\n%s" % out)
    for r in (0, 1):
        if (chaos[r]["digest"] != clean[r]["digest"]
                or chaos[r]["head"] != clean[r]["head"]):
            sys.exit("ci_checks: rank %d resumed run diverged from the "
                     "uninterrupted twin:\n  clean %r\n  chaos %r"
                     % (r, clean[r], chaos[r]))
finally:
    shutil.rmtree(workdir, ignore_errors=True)
print("ci_checks: preemption smoke OK (2-proc SIGTERM -> exit-75 "
      "relaunch; per-rank params+history+audit bit-identical, 0 "
      "divergences)")
EOF

# MFU smoke: a short CPU linear fit with device telemetry on must leave
# compiled-program analytics behind — /xla serves nonzero flops for
# linear.step, the bench-detail assembly (same goodput.attribute path)
# carries a gateable sgd_mfu, and the extraction's second lowering must
# not show up as a post-warmup recompile. bench-gate --smoke already ran
# above, so a regressing sgd_mfu fails this script either way.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" DMLC_TPU_PEAK_FLOPS=1e6 \
python - <<'EOF'
import json, os, shutil, sys, tempfile, time, urllib.request

import numpy as np

import bench
from dmlc_tpu import obs
from dmlc_tpu.models import LinearLearner
from dmlc_tpu.obs import device_telemetry as dt
from dmlc_tpu.obs import goodput, plane, xla_cost

NF, ROWS = 12, 400
rng = np.random.RandomState(7)
workdir = tempfile.mkdtemp(prefix="dmlc_mfu_smoke_")
svm = os.path.join(workdir, "m.svm")
with open(svm, "w") as fh:
    for i in range(ROWS):
        ids = np.sort(rng.choice(NF, size=1 + i % 4, replace=False))
        fh.write("%d %s\n" % (i % 2, " ".join(
            "%d:%.4f" % (j, rng.rand()) for j in ids)))

dt.reset()
t0 = time.time()
learner = LinearLearner(objective="logistic", learning_rate=0.1,
                        num_features=NF)
list(learner.fit_uri(svm, batch_size=64, epochs=1, num_features=NF))
warm = dict(dt.compile_counts())
list(learner.fit_uri(svm, batch_size=64, epochs=2, num_features=NF))
wall = max(time.time() - t0, 1e-9)
if dict(dt.compile_counts()) != warm:
    sys.exit("ci_checks: mfu smoke recompiled past warmup: %r -> %r"
             % (warm, dt.compile_counts()))

reg = obs.registry()
flat = reg.flat_values()
if flat.get('dmlc_xla_recompiles_total{fn="linear.step"}', 0.0):
    sys.exit("ci_checks: mfu smoke tripped the recompile sentinel")
sites = xla_cost.sites_from_flat(flat)
if sites.get("linear.step", {}).get("flops", 0.0) <= 0.0:
    sys.exit("ci_checks: no analyzed linear.step in the registry: %r"
             % sorted(sites))

# the /xla endpoint end to end, fed by the worker's own payload blob
sp = plane.StatusPlane(num_workers=1)
blob, _ = plane.build_payload(rank=0, epoch=2, reg=reg)
sp.note_payload(0, json.loads(blob), time.time_ns())
srv = plane.StatusServer(sp, port=0)
srv.start()
try:
    url = "http://127.0.0.1:%d/xla" % srv.port
    with urllib.request.urlopen(url, timeout=10) as resp:
        body = json.loads(resp.read())
finally:
    srv.close()
served = body.get("ranks", {}).get("0", {}).get("linear.step", {})
if served.get("flops", 0.0) <= 0.0:
    sys.exit("ci_checks: /xla served no linear.step flops: %r" % body)

# the bench-detail assembly: same attribute() call bench.py makes,
# against the tiny DMLC_TPU_PEAK_FLOPS ceiling set for this smoke
extra = {"xla": xla_cost.detail_section()}
att = goodput.attribute(flat, wall, current=flat)
if att.get("mfu") is not None:
    extra["sgd_mfu"] = att["mfu"]
if not extra["xla"]["sites"].get("linear.step"):
    sys.exit("ci_checks: bench detail xla section lost linear.step")
if extra.get("sgd_mfu", 0.0) <= 0.0:
    sys.exit("ci_checks: bench detail carries no sgd_mfu (att=%r)"
             % {k: att.get(k) for k in ("mfu", "compute", "counters")})
if bench.BENCH_DIRECTIONS.get("sgd_mfu") != "higher":
    sys.exit("ci_checks: sgd_mfu is not gated higher-is-better")
shutil.rmtree(workdir, ignore_errors=True)
print("ci_checks: mfu smoke OK (/xla serves linear.step flops; "
      "sgd_mfu %.4f rides the detail record; 0 post-warmup recompiles)"
      % extra["sgd_mfu"])
EOF

echo "ci_checks: all checks passed"
