#!/usr/bin/env python
"""Lint faultpoint sites and DMLC_TPU_* knobs against their registries.

The resilience layer's contract is that every fault-injection site is
discoverable: a chaos author reads the catalog in docs/robustness.md and
writes a ``DMLC_TPU_FAULTS`` spec from it. A faultpoint that exists only
in source silently weakens that contract, and a documented site that no
longer exists makes specs silently inert. Same story for env knobs: a
``DMLC_TPU_*`` variable read anywhere in the tree must be registered in
``params.knobs.KNOWN_KNOBS`` (and thereby documented), or deployments
cannot know it exists.

Mirrors scripts/check_metric_names.py: walks dmlc_tpu/ + bench.py, and
fails when

- a ``faultpoint("...")`` site is not documented (backticked) in
  docs/robustness.md, or is documented but no longer planted (stale
  catalog), or does not follow the ``area.name`` site grammar
  (lowercase dotted segments), or
- a ``DMLC_TPU_*`` literal appears in source without being listed in
  ``KNOWN_KNOBS``, or is listed there but never referenced anywhere
  (dead registry entry), or
- a flight-recorder hook ``record_event("kind", ...)`` (obs/flight.py)
  uses an event kind not cataloged in docs/observability.md's
  "Flight recorder event catalog" table, or the catalog lists a kind no
  longer planted — the same discoverability contract as faultpoints,
  since a post-mortem reader greps dumps by these kinds.

Run directly (exit code 0/1) or via tests/test_faultpoint_lint.py.
"""

from __future__ import annotations

import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parents[1]
DOC = ROOT / "docs" / "robustness.md"
OBS_DOC = ROOT / "docs" / "observability.md"
KNOBS = ROOT / "dmlc_tpu" / "params" / "knobs.py"
FLIGHT = ROOT / "dmlc_tpu" / "obs" / "flight.py"

# faultpoint("site") with a literal site — a computed site is invisible
# to this lint and to chaos-spec authors, so sites stay literal
SITE_CALL_RE = re.compile(r"\bfaultpoint\(\s*[\"']([^\"']+)[\"']")
SITE_GRAMMAR_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")
# sites appear backticked in the docs catalog table
DOC_SITE_RE = re.compile(r"`([a-z0-9_]+(?:\.[a-z0-9_]+)+)`")
KNOB_RE = re.compile(r"\bDMLC_TPU_[A-Z0-9_]+\b")
# flight-recorder hooks: record_event("kind", ...) with a literal kind
FLIGHT_CALL_RE = re.compile(r"\brecord_event\(\s*[\"']([^\"']+)[\"']")


def _walk():
    files = sorted(ROOT.glob("dmlc_tpu/**/*.py")) + [ROOT / "bench.py"]
    return [p for p in files if "tests" not in p.parts]


def planted_sites() -> dict:
    """site -> list of relative paths where faultpoint(site) is planted."""
    out: dict = {}
    for path in _walk():
        if path.name == "faults.py" and "resilience" in path.parts:
            continue  # the harness itself defines, not plants, the hook
            # (other resilience modules may legitimately plant sites,
            # e.g. preempt.py's simulated preemption notice)
        text = path.read_text()
        for site in SITE_CALL_RE.findall(text):
            out.setdefault(site, []).append(str(path.relative_to(ROOT)))
    return out


def documented_sites() -> set:
    """Sites listed in the doc's "Faultpoint catalog" table.

    Scoped to that section's table rows on purpose: the rest of the doc
    backticks retry-site labels and module paths that are not
    faultpoints."""
    if not DOC.exists():
        return set()
    text = DOC.read_text()
    marker = "Faultpoint catalog"
    start = text.find(marker)
    if start < 0:
        return set()
    section = text[start:]
    nxt = section.find("\n#", 1)
    if nxt > 0:
        section = section[:nxt]
    out = set()
    for line in section.splitlines():
        if line.lstrip().startswith("|"):
            first_cell = line.split("|")[1] if "|" in line else ""
            out.update(DOC_SITE_RE.findall(first_cell))
    return out


def planted_flight_events() -> dict:
    """event kind -> list of relative paths planting record_event(kind)."""
    out: dict = {}
    for path in _walk():
        if path == FLIGHT:
            continue  # the recorder defines the hook, plants carry kinds
        for kind in FLIGHT_CALL_RE.findall(path.read_text()):
            out.setdefault(kind, []).append(str(path.relative_to(ROOT)))
    return out


def documented_flight_events() -> set:
    """Kinds listed in observability.md's "Flight recorder event catalog"
    table (section-scoped like :func:`documented_sites`)."""
    if not OBS_DOC.exists():
        return set()
    text = OBS_DOC.read_text()
    marker = "Flight recorder event catalog"
    start = text.find(marker)
    if start < 0:
        return set()
    section = text[start:]
    nxt = section.find("\n#", 1)
    if nxt > 0:
        section = section[:nxt]
    out = set()
    for line in section.splitlines():
        if line.lstrip().startswith("|"):
            first_cell = line.split("|")[1] if "|" in line else ""
            out.update(DOC_SITE_RE.findall(first_cell))
    return out


def referenced_knobs() -> dict:
    """knob -> list of relative paths referencing it (knobs.py excluded)."""
    out: dict = {}
    for path in _walk():
        if path == KNOBS:
            continue
        for knob in KNOB_RE.findall(path.read_text()):
            out.setdefault(knob, []).append(str(path.relative_to(ROOT)))
    return out


def known_knobs() -> set:
    # read KNOWN_KNOBS from source text, not by import: the lint must
    # not depend on the package being importable to report on it
    if not KNOBS.exists():
        return set()
    return set(KNOB_RE.findall(KNOBS.read_text()))


def lint() -> list:
    errors = []
    sites = planted_sites()
    documented = documented_sites()
    if not sites:
        errors.append(
            "no faultpoint() sites found under dmlc_tpu/ — the lint's "
            "call-site regex is probably out of sync with the faults API"
        )
    if not DOC.exists():
        errors.append(f"missing {DOC.relative_to(ROOT)}")
    for site, paths in sorted(sites.items()):
        where = ", ".join(paths[:3])
        if not SITE_GRAMMAR_RE.match(site):
            errors.append(
                f"{site}: faultpoint sites are lowercase dotted "
                f"<area>.<name> segments  [{where}]"
            )
        if documented and site not in documented:
            errors.append(
                f"{site}: not documented in docs/robustness.md  [{where}]"
            )
    for site in sorted(documented - set(sites)):
        errors.append(
            f"{site}: documented in docs/robustness.md but never planted "
            "in source"
        )
    events = planted_flight_events()
    documented_events = documented_flight_events()
    for kind, paths in sorted(events.items()):
        where = ", ".join(paths[:3])
        if not SITE_GRAMMAR_RE.match(kind):
            errors.append(
                f"{kind}: flight-recorder event kinds are lowercase "
                f"dotted <area>.<name> segments  [{where}]"
            )
        if kind not in documented_events:
            errors.append(
                f"{kind}: flight-recorder event not cataloged in "
                f"docs/observability.md  [{where}]"
            )
    for kind in sorted(documented_events - set(events)):
        errors.append(
            f"{kind}: cataloged in docs/observability.md but no "
            "record_event() plants it"
        )
    knobs = referenced_knobs()
    known = known_knobs()
    if not known:
        errors.append(
            "no DMLC_TPU_* knobs found in params/knobs.py — KNOWN_KNOBS "
            "is missing or the knob regex is out of sync"
        )
    for knob, paths in sorted(knobs.items()):
        where = ", ".join(sorted(set(paths))[:3])
        if knob not in known:
            errors.append(
                f"{knob}: referenced in source but not registered in "
                f"params/knobs.py KNOWN_KNOBS  [{where}]"
            )
    for knob in sorted(known - set(knobs)):
        errors.append(
            f"{knob}: registered in params/knobs.py but never referenced "
            "anywhere else in the tree"
        )
    return errors


def main() -> int:
    errors = lint()
    for err in errors:
        print(f"check_faultpoints: {err}")
    if errors:
        print(f"check_faultpoints: {len(errors)} error(s)")
        return 1
    print(
        f"check_faultpoints: {len(planted_sites())} faultpoint site(s), "
        f"{len(planted_flight_events())} flight event kind(s), "
        f"{len(known_knobs())} knob(s) OK"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
