#!/bin/bash
# Round-long TPU tunnel watcher: probe every PERIOD seconds; the moment the
# tunnel answers, run the full tpu_measure.py harvest and stop.  Partial
# results land in OUT even if a later step hangs (tpu_measure runs each
# step in its own subprocess with a hard timeout).
#
# Usage: scripts/tpu_watch.sh [OUT_DIR] [PERIOD_S] [MAX_HOURS]
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
OUT="${1:-/tmp/dmlc_tpu_bench/tpu_sweep}"
PERIOD="${2:-600}"
MAX_HOURS="${3:-11}"
DEADLINE=$(( $(date +%s) + MAX_HOURS * 3600 ))
mkdir -p "$OUT"
LOG="$OUT/watch.log"
echo "[tpu_watch] start $(date -u +%FT%TZ) period=${PERIOD}s deadline_h=${MAX_HOURS}" >> "$LOG"
attempt=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  attempt=$((attempt+1))
  t0=$(date +%s)
  if timeout 120 python -c "import jax; assert jax.devices()[0].platform != 'cpu'; print('up:', jax.devices())" >> "$LOG" 2>&1; then
    echo "[tpu_watch] TUNNEL UP at attempt $attempt $(date -u +%FT%TZ) — harvesting" >> "$LOG"
    timeout 5400 python "$REPO/scripts/tpu_measure.py" --out "$OUT" >> "$LOG" 2>&1
    rc=$?
    echo "[tpu_watch] harvest rc=$rc $(date -u +%FT%TZ)" >> "$LOG"
    if [ $rc -eq 0 ] && [ -s "$OUT/summary.json" ]; then
      # land the evidence in the repo even if nobody is at the wheel:
      # copy the harvest into the committed artifacts dir (the location
      # bench.py's harvest embedding searches last) and commit it
      mkdir -p "$REPO/artifacts/tpu_sweep"
      cp "$OUT"/*.json "$REPO/artifacts/tpu_sweep/" 2>> "$LOG" || true
      # the harvest's detail_path points into the transient OUT dir; the
      # committed copy must point at its committed sibling instead
      python - "$REPO/artifacts/tpu_sweep/bench.json" <<'PYEOF' >> "$LOG" 2>&1 || true
import json, sys
path = sys.argv[1]
with open(path) as fh:
    doc = json.load(fh)
if doc.get("extra", {}).get("detail_path"):
    doc["extra"]["detail_path"] = path.replace("bench.json", "bench_detail.json")
    with open(path, "w") as fh:
        json.dump(doc, fh)
PYEOF
      ( cd "$REPO" && git add artifacts/tpu_sweep \
          && git commit -q -m "Add TPU measurement harvest (tpu_measure.py sweep artifacts)" ) \
          >> "$LOG" 2>&1 || true
      echo "[tpu_watch] DONE" >> "$LOG"
      exit 0
    fi
    # harvest failed mid-way (tunnel died again?) — keep watching
  else
    echo "[tpu_watch] attempt $attempt down ($(( $(date +%s) - t0 ))s) $(date -u +%FT%TZ)" >> "$LOG"
  fi
  sleep "$PERIOD"
done
echo "[tpu_watch] deadline reached without a successful harvest" >> "$LOG"
exit 1
