#!/usr/bin/env python
"""Collective benchmark tier (BASELINE north star: grad-allreduce ICI
bandwidth utilization; reference analog: the tier-2 throughput harnesses,
test/libsvm_parser_test.cc:23-35, rebuilt for the collective layer).

Four measurements, all hermetic on one host:

- socket tree allreduce GB/s (loopback multi-process, latency-bound size)
- socket ring allreduce GB/s (loopback multi-process, bandwidth-bound size)
- device psum: jit-compiled allreduce step time and achieved bytes/s over
  the mesh axis on whatever devices exist (1 real TPU chip today; a virtual
  CPU mesh covers the sharding shapes) — payload re-staged from host numpy
  each step, i.e. the legacy DeviceEngine round-trip shape. When >1 real
  TPU device is present, estimated ICI utilization = achieved algorithm
  bandwidth / peak (``DMLC_TPU_ICI_PEAK_GBPS`` per-direction per-link,
  default 45 for v5e).
- SPMD in-graph step (``spmd_psum_step_gbps``, ``ici_utilization``): the
  training hot path — donated device-resident params, sharded grads, the
  allreduce a psum traced INSIDE the jitted step; zero host bytes moved.

``collective_metrics()`` returns a flat dict merged into bench.py's JSON
line; ``python bench_collective.py`` prints it standalone.
"""

from __future__ import annotations

import json
import multiprocessing as mp
import os
import sys
import time
from contextlib import contextmanager

REPO = os.path.dirname(os.path.abspath(__file__))

# (metric key, payload bytes, forced topology)
DEFAULT_SOCKET_CASES = (
    ("socket_tree_64k", 64 << 10, "tree"),
    ("socket_ring_8m", 8 << 20, "ring"),
)
# DMLC_TPU_BENCH_SOCKET_WORLD re-derives the tree/ring crossover at other
# world sizes on capable hosts (socket_engine.ring_threshold_bytes notes
# why the world=4 figure shouldn't be trusted at 8+)
DEFAULT_SOCKET_WORLD = int(os.environ.get("DMLC_TPU_BENCH_SOCKET_WORLD", 4))
DEFAULT_SOCKET_ITERS = 10


@contextmanager
def forced_topology(engine, topo: str):
    """Force one allreduce topology on ``engine`` for the block: "ring"
    (threshold 0) or "tree" (threshold 2**62). Restores the CONSTRUCTED
    ``ring_threshold_bytes`` on exit — including any
    DMLC_TPU_RING_THRESHOLD_BYTES override the engine applied at build
    time, and on the exception path — so collectives after the block
    (the straggler-max allreduce below) honor the engine's real
    crossover. Previously a comment-only contract inline in the bench
    worker; as a context manager the restore is unit-testable
    (tests/test_bench_collective.py)."""
    constructed = engine.ring_threshold_bytes
    engine.ring_threshold_bytes = 0 if topo == "ring" else (1 << 62)
    try:
        yield engine
    finally:
        engine.ring_threshold_bytes = constructed


def _socket_bench_worker(uri, port, world, cases, iters, q):
    """Subprocess body: rendezvous, then timed allreduce loops per case.
    Per-case time is the max across ranks (allreduce 'max' of the local
    time), so the reported bandwidth is the straggler-bound figure."""
    sys.path.insert(0, REPO)
    import numpy as np

    from dmlc_tpu.collective.socket_engine import SocketEngine

    engine = SocketEngine(
        tracker_uri=uri, tracker_port=port, world_size=world
    )
    try:
        out = {}
        for name, nbytes, topo in cases:
            arr = np.ones(max(1, nbytes // 4), dtype=np.float32)
            with forced_topology(engine, topo):
                engine.allreduce(arr)  # warmup (first ring call opens buffers)
                t0 = time.perf_counter()
                for _ in range(iters):
                    engine.allreduce(arr)
                local_dt = (time.perf_counter() - t0) / iters
            worst = float(
                engine.allreduce(
                    np.array([local_dt], dtype=np.float64), op="max"
                )[0]
            )
            out[name + "_gbps"] = round(nbytes / worst / 1e9, 6)
        if engine.rank == 0:
            q.put(out)
    finally:
        engine.shutdown()


def socket_allreduce_metrics(
    world: int = DEFAULT_SOCKET_WORLD,
    cases=DEFAULT_SOCKET_CASES,
    iters: int = DEFAULT_SOCKET_ITERS,
    timeout: float = 120.0,
) -> dict:
    """Loopback tracker + ``world`` worker processes; tree and ring
    allreduce payload GB/s at latency- and bandwidth-bound sizes."""
    from dmlc_tpu.tracker.rendezvous import RabitTracker

    tracker = RabitTracker("127.0.0.1", world, port=19290, port_end=19390)
    tracker.start(world)
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(
            target=_socket_bench_worker,
            args=("127.0.0.1", tracker.port, world, tuple(cases), iters, q),
        )
        for _ in range(world)
    ]
    for p in procs:
        p.start()
    try:
        out = q.get(timeout=timeout)
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
        tracker.close()
    out["socket_world"] = world
    # honesty marker: `world` processes + tracker share this host's CPUs,
    # so loopback figures are contention floors, not network bandwidth
    out["socket_note"] = (
        f"loopback, {world} procs on {os.cpu_count() or 1} cpu(s): "
        "contention floor"
    )
    return out


def allreduce_algo_metrics(n: int, nbytes: int, dt: float,
                           platform: str) -> dict:
    """Pure estimator for the >1-device psum tier (factored out so the
    virtual-mesh tests exercise it without real multi-chip hardware).
    Ring-allreduce moves 2(n-1)/n × size per device, so achieved
    algorithm bandwidth = that volume / step time; on TPU the ICI
    utilization is achieved / peak (``DMLC_TPU_ICI_PEAK_GBPS``
    per-direction per-link, default 45 for v5e)."""
    algo_bytes = 2 * (n - 1) / n * nbytes  # per-device wire volume
    metrics = {"psum_algo_gbps": round(algo_bytes / dt / 1e9, 3)}
    if platform == "tpu":
        peak = float(os.environ.get("DMLC_TPU_ICI_PEAK_GBPS", 45.0)) * 1e9
        metrics["psum_ici_utilization"] = round((algo_bytes / dt) / peak, 3)
    return metrics


def crossover_sweep(world: int = 4,
                    sizes=(64 << 10, 256 << 10, 1 << 20, 2 << 20, 4 << 20),
                    iters: int = 4) -> dict:
    """Tree vs ring allreduce at a ladder of sizes → the measured
    crossover (how SocketEngine.ring_threshold_bytes was derived; rerun
    on a new host/network to re-justify it). Returns per-size GB/s for
    both topologies plus ``crossover_bytes``: the first size where the
    ring at least matches the tree (None if the tree wins everywhere)."""
    cases = []
    for s in sizes:
        cases.append((f"tree_{s}", s, "tree"))
        cases.append((f"ring_{s}", s, "ring"))
    out = socket_allreduce_metrics(world=world, cases=tuple(cases),
                                   iters=iters)
    crossover = None
    for s in sizes:
        if out[f"ring_{s}_gbps"] >= out[f"tree_{s}_gbps"]:
            crossover = s
            break
    out["crossover_bytes"] = crossover
    return out


def _maybe_force_cpu_devices() -> None:
    """DMLC_TPU_BENCH_CPU_DEVICES: shape-coverage mode on a virtual CPU
    mesh. Every jax-touching tier must call this BEFORE jax.devices() —
    the interpreter may boot with a TPU hook whose backend init hangs on
    a dead tunnel, and config.update (not the env var) is what still
    works after jax was pre-imported (same trick as tests/conftest)."""
    import jax

    if os.environ.get("DMLC_TPU_BENCH_CPU_DEVICES"):
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                + os.environ["DMLC_TPU_BENCH_CPU_DEVICES"]
            ).strip()
        jax.config.update("jax_platforms", "cpu")


def device_psum_metrics(payload_mb: float = 32.0, iters: int = 20) -> dict:
    """Jitted psum-allreduce step over the device mesh axis: per-step time
    and achieved algorithm bytes/s. Ring-allreduce moves 2(n-1)/n × size
    per device, so achieved_bw = that volume / step time; utilization is
    reported only on real multi-device TPU."""
    import jax  # noqa: F401  (backend touched below)

    _maybe_force_cpu_devices()

    import numpy as np

    from dmlc_tpu.collective.device import make_allreduce_step
    from dmlc_tpu.parallel.mesh import batch_sharding, data_parallel_mesh

    devices = jax.devices()
    n = len(devices)
    mesh = data_parallel_mesh(devices)
    step = make_allreduce_step(mesh, axis="dp")

    elems = (int(payload_mb * (1 << 20) // 4) // n) * n
    host = np.ones(elems, dtype=np.float32)
    sharding = batch_sharding(mesh)

    def one_step():
        # donation consumes the input each call; re-placing from a host
        # array is itself pipelined H2D, kept outside the timed region
        x = jax.device_put(host, sharding)
        jax.block_until_ready(x)
        t0 = time.perf_counter()
        out = step(x)
        jax.block_until_ready(out)
        return time.perf_counter() - t0

    one_step()  # compile + warmup
    dt = min(one_step() for _ in range(iters))

    nbytes = elems * 4
    metrics = {
        "psum_devices": n,
        "psum_platform": devices[0].platform,
        "psum_payload_mb": round(nbytes / (1 << 20), 1),
        "psum_step_ms": round(dt * 1e3, 3),
    }
    if n > 1:
        metrics.update(
            allreduce_algo_metrics(n, nbytes, dt, devices[0].platform)
        )
    else:
        # single device: psum over a size-1 axis is a pass-through; this
        # measures step dispatch + donation only, not a collective
        metrics["psum_single_device_gbps"] = round(nbytes / dt / 1e9, 3)
    return metrics


def spmd_psum_step_metrics(payload_mb: float = 32.0, iters: int = 20) -> dict:
    """The tentpole hot path in isolation: a jitted SPMD SGD-shaped step
    whose gradient allreduce is an in-graph psum over the mesh axis.
    Contrast ``device_psum_metrics``, which re-stages its payload from
    host numpy every step (the legacy DeviceEngine round-trip): here the
    params are DONATED and carried device-to-device across iterations and
    the sharded grads stay resident, exactly like LinearLearner's fit
    loop — the measured figure is the in-graph collective + update with
    zero host bytes on the path.

    Reports ``spmd_psum_step_gbps`` (achieved algorithm bytes/s through
    the psum: ring volume 2(n-1)/n × payload per device) and, on real
    multi-device TPU, ``ici_utilization`` (achieved / peak,
    ``DMLC_TPU_ICI_PEAK_GBPS`` per-direction per-link, default 45 for
    v5e). Both are gated higher-is-better by bench-gate
    (obs/sentry.py)."""
    import jax

    _maybe_force_cpu_devices()

    import numpy as np

    from dmlc_tpu.obs.device_telemetry import instrumented_jit
    from dmlc_tpu.parallel.mesh import (
        batch_sharding, data_parallel_mesh, replicated_sharding,
    )
    from dmlc_tpu.utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    devices = jax.devices()
    n = len(devices)
    mesh = data_parallel_mesh(devices)
    elems = int(payload_mb * (1 << 20) // 4)

    def _sharded(w, g):
        # the train-step shape: in-graph allreduce then SGD apply; the
        # reduced grads never exist on the host
        red = jax.lax.psum(g, "dp")
        return w - 0.01 * red[0]

    step = instrumented_jit(
        shard_map(
            _sharded, mesh=mesh, in_specs=(P(), P("dp")), out_specs=P()
        ),
        "bench.spmd_step",
        donate_argnums=(0,),
    )
    w = jax.device_put(
        np.zeros(elems, dtype=np.float32), replicated_sharding(mesh)
    )
    g = jax.device_put(
        np.ones((n, elems), dtype=np.float32), batch_sharding(mesh)
    )
    w = step(w, g)
    float(w[0])  # compile + warmup + readback fence
    # amortized pipelined timing (see device_engine_allreduce_metrics):
    # back-to-back dispatch, ended on a 1-element D2H read that cannot
    # complete early
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            w = step(w, g)
        float(w[0])
        dt = (time.perf_counter() - t0) / iters
        best = dt if best is None else min(best, dt)

    nbytes = elems * 4
    metrics = {
        "spmd_devices": n,
        "spmd_platform": devices[0].platform,
        "spmd_payload_mb": round(nbytes / (1 << 20), 1),
        "spmd_step_ms": round(best * 1e3, 3),
    }
    if n > 1:
        algo_bytes = 2 * (n - 1) / n * nbytes
        metrics["spmd_psum_step_gbps"] = round(algo_bytes / best / 1e9, 3)
        if devices[0].platform == "tpu":
            peak = float(os.environ.get("DMLC_TPU_ICI_PEAK_GBPS", 45.0)) * 1e9
            metrics["ici_utilization"] = round((algo_bytes / best) / peak, 3)
    else:
        # size-1 axis: the psum is a pass-through — step dispatch + apply
        # rate only, still useful as the key's single-device floor
        metrics["spmd_psum_step_gbps"] = round(nbytes / best / 1e9, 3)
    return metrics


def grad_bucket_metrics(iters: int = 8) -> dict:  # min-of-8 from the tier's
    # first artifact on (r04): each iter moves a ~25 MB pytree, so 8 bounds
    # the tier's tunnel time; the within-run fused-vs-per-tensor A/B is the
    # quantity of record, not the absolute ms
    """Fused-bucket vs per-tensor gradient allreduce A/B on whatever
    devices exist (preparing for the ICI-utilization target before
    multi-chip hardware does: one concatenated psum per step vs one psum
    per leaf). The pytree mimics a small transformer's grad structure —
    many leaves of very different sizes — where combiner behavior actually
    matters."""
    import jax
    import numpy as np

    _maybe_force_cpu_devices()  # standalone-callable without a tunnel

    from dmlc_tpu.collective.device import make_allreduce_step
    from dmlc_tpu.parallel.mesh import batch_sharding, data_parallel_mesh

    devices = jax.devices()
    n = len(devices)
    mesh = data_parallel_mesh(devices)
    sharding = batch_sharding(mesh)

    rng = np.random.RandomState(0)
    # ~24 MB over 26 leaves: embeddings, per-layer qkvo + mlp + norms
    shapes = [(1024, 512), (512, 512), (512, 512), (512, 512), (512, 512),
              (512, 2048), (2048, 512), (512,), (512,)] * 2 + [
        (1024, 512), (8, 512), (512,), (512,), (2048,), (2048,), (512, 512),
        (512,)]
    grads = {
        f"g{i}": rng.randn(n, *s).astype(np.float32)
        for i, s in enumerate(shapes)
    }  # leading dim shards over dp
    nbytes = sum(g.nbytes for g in grads.values())

    out = {"bucket_payload_mb": round(nbytes / (1 << 20), 1),
           "bucket_leaves": len(shapes)}
    for key, bucket in (("bucket_fused_ms", True),
                        ("bucket_per_tensor_ms", False)):
        step = make_allreduce_step(mesh, axis="dp", bucket=bucket)

        def one():
            x = {k: jax.device_put(v, sharding) for k, v in grads.items()}
            jax.block_until_ready(x)
            t0 = time.perf_counter()
            y = step(x)
            jax.block_until_ready(y)
            return time.perf_counter() - t0

        one()  # compile + warmup
        out[key] = round(min(one() for _ in range(iters)) * 1e3, 3)
    return out


def device_engine_allreduce_metrics(
    payload_mb: float = 32.0, iters: int = 20
) -> dict:
    """DeviceEngine.allreduce's jitted reduction path: a [world, N] array
    with its leading dim sharded over the process axis, reduced to a
    replicated output (the O(N) XLA AllReduce the engine runs for host
    arrays — the data plane, not just control scalars). With one process
    the measured figure is the on-chip reduction + replication rate; with
    more it is the cross-host AllReduce."""
    import jax
    import numpy as np

    from dmlc_tpu.collective.device import DeviceEngine

    eng = DeviceEngine()
    elems = int(payload_mb * (1 << 20) // 4)
    arr = np.ones(elems, dtype=np.float32)

    if eng.world_size > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(eng._process_mesh(), P("proc"))
        garr = jax.make_array_from_process_local_data(
            sharding, arr[None], (eng.world_size,) + arr.shape
        )
        moved = elems * 4  # per-link payload of the cross-host AllReduce
        key = "engine_allreduce_gbps"
    else:
        # one process: the engine short-circuits, and a [1, N] reduce
        # compiles to a no-op — measure a real W-way on-chip reduction
        # instead (the compute half of the allreduce; HBM-bound figure)
        W = 8
        garr = jax.device_put(np.ones((W, elems), dtype=np.float32))
        moved = W * elems * 4
        key = "engine_reduce_single_process_gbps"
    fn = eng._reduce_fn("sum")
    # amortized pipelined timing with a value readback fence: through a
    # tunneled runtime, per-call block_until_ready can cost a ~66 ms round
    # trip (or return early) regardless of compute, so neither per-call
    # timing nor trusting the fence is sound; dispatch iters back-to-back
    # and end on a 1-element D2H read, which cannot complete early. On a
    # local host this converges to the HBM-bound figure.
    float(fn(garr)[0])  # compile + warmup + fence
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = fn(garr)
        float(out[0])  # readback fence
        dt = (time.perf_counter() - t0) / iters
        best = dt if best is None else min(best, dt)
    return {
        "engine_allreduce_world": eng.world_size,
        "engine_allreduce_payload_mb": round(elems * 4 / (1 << 20), 1),
        key: round(moved / best / 1e9, 3),
    }


def collective_metrics(device_ok: bool = True) -> dict:
    """The bench.py hook: flat metric dict; failures are per-tier so one
    broken tier cannot hide the other. device_ok=False (backend init probe
    failed — jax.devices() would hang) skips the two jax tiers; the socket
    tier never touches jax."""
    out = {}
    try:
        out.update(socket_allreduce_metrics())
    except Exception as err:
        out["socket_allreduce_error"] = str(err)
    cpu_mode = bool(os.environ.get("DMLC_TPU_BENCH_CPU_DEVICES"))
    if not device_ok and not cpu_mode:
        out["device_tiers_skipped"] = "jax backend unavailable"
        return out
    # DMLC_TPU_BENCH_CPU_DEVICES: the psum tier forces itself onto virtual
    # CPU devices (no TPU backend needed), so it runs even when the probe
    # failed; the engine tier does NOT self-force and would hang on a dead
    # tunnel, so it still honors the probe.
    try:
        out.update(device_psum_metrics())
    except Exception as err:
        out["psum_error"] = str(err)
    try:
        out.update(spmd_psum_step_metrics())
    except Exception as err:
        out["spmd_step_error"] = str(err)
    try:
        out.update(grad_bucket_metrics())
    except Exception as err:
        out["bucket_error"] = str(err)
    if not device_ok:
        out["engine_tier_skipped"] = "jax backend unavailable"
        return out
    try:
        out.update(device_engine_allreduce_metrics())
    except Exception as err:
        out["engine_allreduce_error"] = str(err)
    return out


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    print(json.dumps(collective_metrics()))
